"""Command-line entry point.

Reference analog: src/main.cc (gflags -> Postoffice -> App::Create(config)
-> run) plus script/local.sh. The reference dispatches scheduler / server /
worker roles as processes; on TPU the roles collapse into one SPMD program,
so the CLI surface is: a config file picks the app and solver, flags pick
the run mode.

Usage:
  python -m parameter_server_tpu.cli train  --app_file cfg.json [--model_out m.txt]
  python -m parameter_server_tpu.cli evaluate --app_file cfg.json --model m.txt
"""

from __future__ import annotations

import argparse
import json
import sys

from parameter_server_tpu.utils.config import PSConfig, load_config


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="parameter_server_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    tr = sub.add_parser("train", help="train the configured app")
    tr.add_argument("--app_file", required=True, help="JSON/TOML PSConfig")
    tr.add_argument("--model_out", default="", help="text model dump path")
    tr.add_argument(
        "--ckpt_dir", default="",
        help="checkpoint directory (multi-host: pass the SAME flags on "
        "every host — saving ends in a cross-host barrier)",
    )
    tr.add_argument("--resume", action="store_true", help="resume from ckpt_dir")
    tr.add_argument(
        "--report_interval", type=int, default=50, help="steps between reports"
    )
    # multi-host pod bootstrap (ref: -scheduler ip:port -my_node ...): run
    # one identical process per host with the same coordinator address
    tr.add_argument(
        "--coordinator", default="",
        help="host:port of process 0 for jax.distributed (multi-host pods)",
    )
    tr.add_argument("--num_processes", type=int, default=1)
    tr.add_argument("--process_id", type=int, default=0)
    # tier composition: dynamic shard assignment from the wire tier's
    # Coordinator instead of the static per-host file split
    tr.add_argument(
        "--pool_coordinator", default="",
        help="host:port of a wire-tier Coordinator assigning file shards "
        "dynamically across pod hosts (PodTrainer.train_files_dynamic)",
    )
    tr.add_argument(
        "--pool_serve", action="store_true",
        help="process 0 hosts the pool Coordinator at --pool_coordinator "
        "itself (no external scheduler process needed)",
    )
    tr.add_argument(
        "--trace_dir", default="",
        help="arm distributed tracing (utils/trace.py): spans exported as "
        "Chrome trace-event JSON into this dir (open in Perfetto); "
        "overrides config [trace] trace_dir and PS_TRACE_DIR",
    )

    ev = sub.add_parser("evaluate", help="evaluate a dumped model")
    ev.add_argument("--app_file", required=True)
    ev.add_argument("--model", required=True, help="text model dump")
    ev.add_argument("--data", nargs="*", default=None, help="override val files")

    # multi-process tier (ref: main.cc role flags + script/local.sh)
    nd = sub.add_parser("node", help="run one scheduler/server/worker process")
    nd.add_argument("--role", required=True, choices=("scheduler", "server", "worker"))
    nd.add_argument("--rank", type=int, default=0, help="ref: -my_node id")
    nd.add_argument("--scheduler", required=True, help="host:port (ref: -scheduler)")
    nd.add_argument("--num_servers", type=int, required=True)
    nd.add_argument("--num_workers", type=int, required=True)
    nd.add_argument("--app_file", required=True)
    nd.add_argument("--model_out", default="")
    nd.add_argument(
        "--bind_host", default="127.0.0.1",
        help="server bind address (0.0.0.0 to accept remote workers)",
    )
    nd.add_argument(
        "--advertise_host", default="",
        help="routable hostname published to the coordinator "
        "(defaults to bind_host)",
    )
    nd.add_argument(
        "--ckpt_dir", default="",
        help="server recovery dir: resume this range's dump if present; "
        "periodic dumps per [fault] server_ckpt_interval_s",
    )
    nd.add_argument(
        "--fault_plan", default="",
        help="chaos spec (parallel/chaos.py DSL) armed on this node's "
        "RpcServers; overrides PS_FAULT_PLAN and the config's [fault] "
        "fault_plan",
    )
    nd.add_argument("--fault_seed", type=int, default=0)
    nd.add_argument(
        "--trace_dir", default="",
        help="arm distributed tracing on this node (overrides config "
        "[trace] trace_dir and PS_TRACE_DIR)",
    )

    cv = sub.add_parser(
        "convert",
        help="offline text -> columnar block cache conversion "
        "(ref: data/text2proto + SlotReader's parse-once cache)",
    )
    cv.add_argument("--app_file", required=True, help="JSON/TOML PSConfig")
    cv.add_argument(
        "--cache_dir", default="",
        help="output cache dir (defaults to the config's data.cache_dir; "
        "if you override it here, set data.cache_dir to the same path in "
        "the TRAINING config or the cache will never be read)",
    )

    la = sub.add_parser(
        "launch", help="spawn a local multi-process run (ref: script/local.sh)"
    )
    la.add_argument("--app_file", required=True)
    la.add_argument("--num_servers", type=int, default=1)
    la.add_argument("--num_workers", type=int, default=1)
    la.add_argument("--model_out", default="")
    la.add_argument(
        "--fault_plan", default="",
        help="chaos spec (parallel/chaos.py DSL) armed on EVERY spawned "
        "node via PS_FAULT_PLAN — seeded drop/delay/disconnect/duplicate "
        "frame faults for recovery drills",
    )
    la.add_argument("--fault_seed", type=int, default=0)
    la.add_argument(
        "--trace_dir", default="",
        help="arm distributed tracing on EVERY spawned node via "
        "PS_TRACE_DIR: each process exports a Chrome trace-event JSON "
        "into this dir; merge with utils/trace.py:merge_trace_dir and "
        "open in Perfetto",
    )
    la.add_argument(
        "--blackbox_dir", default="",
        help="arm the flight recorder + stall watchdog on EVERY spawned "
        "node via PS_BLACKBOX_DIR: each process leaves a black-box dump "
        "behind for `cli postmortem` to merge",
    )

    st = sub.add_parser(
        "stats",
        help="print the cluster telemetry table from a live coordinator "
        "(the reference scheduler's dashboard): per-node counters + "
        "merged per-command latency histograms (count/p50/p99)",
    )
    st.add_argument(
        "--scheduler", required=True, help="coordinator host:port"
    )

    tp = sub.add_parser(
        "top",
        help="live cluster dashboard (the operations plane's `top`): "
        "auto-refreshing per-node windowed rates + p99 latencies from "
        "the coordinator's retained heartbeat time series, SLO "
        "burn-rate health per node, active alerts and hot keys",
    )
    tp.add_argument("--scheduler", required=True, help="coordinator host:port")
    tp.add_argument(
        "--interval", type=float, default=2.0,
        help="refresh cadence in seconds",
    )
    tp.add_argument(
        "--window", type=float, default=0.0,
        help="rate/percentile window in seconds (0 = the coordinator's "
        "[timeseries] window_s default)",
    )
    tp.add_argument(
        "--once", action="store_true",
        help="print one frame and exit (scripts / tests)",
    )
    tp.add_argument(
        "--json", action="store_true",
        help="one-shot machine-readable output (implies --once): the "
        "same blocks the dashboard renders — nodes, windowed series, "
        "health, active alerts, audit — as one JSON document for CI "
        "and scripts",
    )

    rg = sub.add_parser(
        "ranges",
        help="the freshness plane's dashboard (`top` over key ranges): "
        "per-range push/pull rates, bytes moved, apply cost and the "
        "REALIZED data-age distribution of serves (server-measured "
        "publish-to-serve age + cache dwell), aggregated cluster-wide "
        "from the coordinator's retained heartbeat time series, with "
        "hot-key heat folded onto the owning range",
    )
    rg.add_argument("--scheduler", required=True, help="coordinator host:port")
    rg.add_argument(
        "--interval", type=float, default=2.0,
        help="refresh cadence in seconds",
    )
    rg.add_argument(
        "--window", type=float, default=0.0,
        help="rate/percentile window in seconds (0 = the coordinator's "
        "[timeseries] window_s default)",
    )
    rg.add_argument(
        "--once", action="store_true",
        help="print one frame and exit (scripts / tests)",
    )
    rg.add_argument(
        "--json", action="store_true",
        help="one-shot machine-readable per-range matrix (implies "
        "--once)",
    )

    au = sub.add_parser(
        "audit",
        help="the live audit plane (streaming protocol sentinel): "
        "violations of the invariants psmc proves offline — "
        "acked-but-unapplied pushes, double applies, RCU version "
        "regressions, SSP staleness overruns, reconnects without "
        "heals, shed storms — detected by the coordinator's streaming "
        "monitors over the heartbeat event bus; one-shot summary or "
        "live follow",
    )
    au.add_argument("--scheduler", required=True, help="coordinator host:port")
    au.add_argument(
        "--interval", type=float, default=2.0,
        help="follow-mode poll cadence in seconds",
    )
    au.add_argument(
        "--once", action="store_true",
        help="print one summary and exit (nonzero when violations "
        "exist — CI drills gate on it)",
    )
    au.add_argument("--json", action="store_true")
    au.add_argument(
        "--recent", type=int, default=20,
        help="recent violations to include in the panel",
    )

    wl = sub.add_parser(
        "whylate",
        help="tail-latency forensics (analysis/critpath.py): stitch "
        "logical push/pull ops across processes and attribute their "
        "wall time to named pipeline segments (client_queue, wire, "
        "server, apply_wait, apply, reply_lane, ssp_wait). Feed it a "
        "PS_TRACE_DIR capture (tail-capture sidecars rescued), a "
        "PS_BLACKBOX_DIR postmortem, or a live cluster via "
        "--scheduler; --baseline gates per-segment p99 budgets with "
        "tiered exit codes (1 = hard regression, 2 = over budget)",
    )
    wl.add_argument(
        "dir", nargs="?", default="",
        help="trace or blackbox capture dir (omit with --scheduler)",
    )
    wl.add_argument(
        "--scheduler", default="",
        help="live mode: read the heartbeat-piggybacked slowest-op "
        "records from this coordinator instead of a capture dir",
    )
    wl.add_argument(
        "--top", type=int, default=5,
        help="slowest ops to list per command",
    )
    wl.add_argument("--json", action="store_true")
    wl.add_argument(
        "--baseline", default="", metavar="FILE",
        help="per-segment latency budgets (JSON: budgets_ms[cmd][seg] "
        "+ hard_factor); exit 1 when a segment p99 exceeds "
        "hard_factor x budget, 2 when it merely exceeds budget "
        "(the pslint --baseline tiering)",
    )
    wl.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite --baseline from this capture's per-segment p99s "
        "(x2 slack)",
    )

    pm = sub.add_parser(
        "postmortem",
        help="merge the black-box dumps of a crashed/stalled cluster "
        "(PS_BLACKBOX_DIR, utils/flightrec.py) into one causal "
        "timeline: cross-process (cid, seq) stitching, anomaly flags "
        "(stalls, acked-but-unapplied pushes, version regressions, "
        "reconnects without heals, shed storms), per-key heat, and an "
        "optional Perfetto-loadable rendering",
    )
    pm.add_argument("dir", help="the blackbox dump directory")
    pm.add_argument(
        "--trace_out", default="",
        help="also write the merged timeline as Chrome trace-event JSON "
        "(open in Perfetto next to a PS_TRACE_DIR trace of the run)",
    )
    pm.add_argument(
        "--tail", type=int, default=40,
        help="merged-timeline events to print in the human report",
    )

    li = sub.add_parser(
        "lint",
        help="run pslint — the project-native static analyzer "
        "(python -m parameter_server_tpu.analysis): lock-order, "
        "blocking-under-lock, settle-exactly-once, counter/config "
        "contracts, trace hygiene, and the quantity-flow triple "
        "(units / clockdomain / idtype); exits nonzero on findings",
    )
    li.add_argument(
        "--checker", action="append", default=None,
        help="run only this checker (repeatable)",
    )
    li.add_argument("--json", action="store_true")
    li.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="gate on no NEW findings vs this JSON baseline (CI mode: "
        "pre-existing debt stays visible but frozen). Matching is "
        "LINE-INSENSITIVE — entries match on (checker, file, message) "
        "as a multiset, so edits above a finding never churn the gate "
        "but a second instance of a baselined finding still fails",
    )
    li.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite --baseline from the current findings",
    )
    li.add_argument(
        "--changed-only", default=None, metavar="REF",
        help="report only findings in files changed vs this git ref "
        "(the analysis still covers the whole package — fast pre-push "
        "iteration, not the gate of record)",
    )

    ck = sub.add_parser(
        "check",
        help="run psmc — the explicit-state protocol model checker "
        "(analysis/model.py over analysis/specs/: exactly-once pushes, "
        "RCU publish/read, SSP clock, chain-replication failover) plus "
        "the spec<->code conformance diff; exits nonzero unless every "
        "model exhausts its bounded state space violation-free AND no "
        "spec assumption has drifted from the code",
    )
    ck.add_argument(
        "--spec", action="append", default=None,
        help="check only this protocol model (repeatable)",
    )
    ck.add_argument(
        "--max-states", type=int, default=200_000,
        help="BFS state cap (capped runs fail: verification demands "
        "exhausting the bounded space)",
    )
    ck.add_argument(
        "--probe-seeds", type=int, default=0,
        help="seeded random walks past a hit cap (bug probing, not "
        "verification)",
    )
    ck.add_argument(
        "--bug", default=None, metavar="KNOB",
        help="check the named seeded-bug variant of one --spec; exit 0 "
        "iff the checker catches it with a counterexample",
    )
    ck.add_argument(
        "--no-conformance", action="store_true",
        help="skip the spec<->code conformance diff (models only)",
    )
    ck.add_argument("--json", action="store_true")

    vf = sub.add_parser(
        "verify",
        help="the one-shot verification meta-command: chain pslint "
        "(--baseline gating), psmc protocol checking, optionally a "
        "live `audit --once` and an offline `whylate --baseline` "
        "budget gate, and fold their verdicts into ONE tiered exit "
        "code (0 clean, 2 soft/over-budget only, 1 any hard failure) "
        "— the single command CI and the bench workflow call",
    )
    vf.add_argument(
        "--lint-baseline", default="", metavar="FILE",
        help="pass through to `lint --baseline` (omit for a plain "
        "zero-findings lint)",
    )
    vf.add_argument(
        "--lint-changed-only", default="", metavar="REF",
        help="pass through to `lint --changed-only REF` (report only "
        "findings in files changed vs the ref; the analysis still "
        "covers the whole package)",
    )
    vf.add_argument(
        "--max-states", type=int, default=200_000,
        help="psmc BFS state cap (see `check --max-states`)",
    )
    vf.add_argument(
        "--scheduler", default="",
        help="also run `audit --once` against this live coordinator "
        "(omitted: the audit stage is skipped)",
    )
    vf.add_argument(
        "--whylate", dest="whylate_dir", default="", metavar="DIR",
        help="also run `whylate` over this trace/blackbox capture dir "
        "(omitted: the whylate stage is skipped)",
    )
    vf.add_argument(
        "--whylate-baseline", default="", metavar="FILE",
        help="per-segment latency budgets for the whylate stage (see "
        "`whylate --baseline`)",
    )
    vf.add_argument("--json", action="store_true")

    bk = sub.add_parser(
        "backend",
        help="drive the canonical linear trainer loop through the "
        "configured transport-neutral KV backend ([mesh] section, "
        "parallel/backend.py): 'mesh' runs in-process GSPMD collectives "
        "over the local device mesh, 'socket' spins loopback "
        "ShardServers — one synthetic workload, either transport, JSON "
        "metrics (AUC, ex/s, payload bytes) on stdout",
    )
    bk.add_argument("--app_file", required=True, help="JSON/TOML PSConfig")
    bk.add_argument(
        "--examples", type=int, default=1 << 14,
        help="synthetic examples to stream through the loop",
    )
    bk.add_argument("--batch", type=int, default=2048)
    bk.add_argument("--nnz", type=int, default=16, help="features/example")
    bk.add_argument(
        "--servers", type=int, default=2,
        help="socket backend only: in-process loopback shard servers",
    )

    ex = sub.add_parser(
        "explore",
        help="budgeted schedule-seed search (analysis/explorer.py): run "
        "a test under PS_SCHED=<seed> for N seeds, persist failing "
        "seeds to the committed corpus, and print the exact replay "
        "line — how an interleaving bug becomes a regression test",
    )
    ex.add_argument(
        "test",
        help="pytest node id to explore (e.g. tests/test_serving.py::"
        "TestServingChaosCoherence::"
        "test_read_your_writes_and_exactly_once_under_chaos)",
    )
    ex.add_argument(
        "--budget", type=int, default=20,
        help="seeds to try (one fresh pytest process per seed)",
    )
    ex.add_argument(
        "--start-seed", type=int, default=1,
        help="first seed of the contiguous budget window",
    )
    ex.add_argument(
        "--corpus", default=None, metavar="FILE",
        help="corpus file failing seeds are merged into (the "
        "explorer-armed tier-1 run replays every seed recorded here); "
        "default: the repo's committed tests/sched_corpus.json, "
        "resolved next to the package so any CWD records to the file "
        "tier-1 actually replays",
    )
    ex.add_argument(
        "--timeout", type=float, default=120.0, metavar="S",
        help="per-seed budget: a seed that wedges the test past this "
        "counts as FAILING (a deadlock interleaving is the find, not "
        "a reason to hang the search)",
    )
    ex.add_argument(
        "--no-record", action="store_true",
        help="print failing seeds without touching the corpus file",
    )
    return p


_KNOWN_APPS = (
    "linear_method", "graph_partition", "sketch", "matrix_fac", "word2vec",
    "wide_deep",
)


def run_train(cfg: PSConfig, args: argparse.Namespace) -> dict:
    if cfg.app not in _KNOWN_APPS:
        # an unknown app would silently fall through to linear_method
        raise SystemExit(
            f"unknown app {cfg.app!r}; known: {sorted(_KNOWN_APPS)}"
        )
    if not cfg.data.files:
        raise SystemExit("config data.files is empty")
    if args.pool_coordinator and not (
        cfg.app == "linear_method"
        and cfg.solver.algo != "darlin"
        and (args.coordinator or cfg.parallel.data_shards * cfg.parallel.kv_shards > 1)
    ):
        # silently ignoring the flag would leave other pod hosts parked on
        # a coordinator this process never starts or contacts
        raise SystemExit(
            "--pool_coordinator requires the pod training path "
            "(linear_method with a >1x1 parallel mesh or --coordinator)"
        )
    if cfg.app == "graph_partition":
        from parameter_server_tpu.models.graph_partition import GraphPartition

        app = GraphPartition(cfg)
        out = app.partition_files(cfg.data.files)
        if args.model_out:
            out["features_dumped"] = app.dump_partition(args.model_out)
        return out
    if cfg.app == "sketch":
        from parameter_server_tpu.models.sketch import SketchApp

        app = SketchApp(cfg)
        app.add_files(cfg.data.files)
        out = app.result()
        if args.model_out:
            out["dumped"] = app.dump_heavy_hitters(args.model_out)
        return out
    if cfg.app == "matrix_fac":
        return _run_train_mf(cfg, args)
    if cfg.app == "word2vec":
        return _run_train_w2v(cfg, args)
    if cfg.app == "wide_deep":
        return _run_train_wd(cfg, args)
    if cfg.solver.algo == "darlin":
        from parameter_server_tpu.data.batch import BatchBuilder
        from parameter_server_tpu.data.reader import MinibatchReader
        from parameter_server_tpu.models.darlin import Darlin
        from parameter_server_tpu.utils.checkpoint import (
            dump_weights_text,
            save_checkpoint,
        )

        if args.resume:
            raise SystemExit(
                "--resume is not supported for the darlin batch solver "
                "(it restarts from its cached column blocks)"
            )
        if args.coordinator:
            # silently ignoring the flag would run N independent solvers
            # clobbering each other's cache/model outputs
            raise SystemExit(
                "--coordinator is not supported for the darlin batch solver "
                "(distributed darlin runs on one process's mesh via "
                "parallel.data_shards/kv_shards)"
            )
        mesh = None
        if cfg.parallel.data_shards * cfg.parallel.kv_shards > 1:
            from parameter_server_tpu.parallel import make_mesh

            mesh = make_mesh(cfg.parallel.data_shards, cfg.parallel.kv_shards)
        app = Darlin(cfg, mesh=mesh)
        # SlotReader behavior: with data.cache_dir set, the first run parses
        # text and writes the columnar block cache; re-runs mmap it instead.
        from parameter_server_tpu.data.blockcache import cached_column_blocks

        res = app.fit_blocks(cached_column_blocks(cfg))
        if args.ckpt_dir:
            save_checkpoint(
                args.ckpt_dir,
                {"w": app.w},
                meta={"algo": "darlin", "num_keys": cfg.data.num_keys},
            )
        if args.model_out:
            dump_weights_text(app.w, args.model_out)
        out = {k: res[k] for k in ("objv", "iters", "nnz_w", "train_auc")}
        if cfg.data.val_files:
            builder = BatchBuilder(
                num_keys=cfg.data.num_keys,
                batch_size=cfg.solver.minibatch,
                max_nnz_per_example=cfg.data.max_nnz_per_example,
            )
            val = list(
                MinibatchReader(cfg.data.val_files, cfg.data.format, builder)
            )
            p = app.predict(val)
            import numpy as np

            from parameter_server_tpu.models import metrics as M

            y = np.concatenate([b.labels[: b.num_examples] for b in val])
            out["val_auc"] = M.auc(y, p)
            out["val_logloss"] = M.logloss(y, p)
        return out

    # pod path: a mesh bigger than 1x1 (or an explicit coordinator) routes
    # the flagship app through PodTrainer over the (data, kv) device mesh
    if args.coordinator or cfg.parallel.data_shards * cfg.parallel.kv_shards > 1:
        from parameter_server_tpu.parallel import runtime as runtime_mod
        from parameter_server_tpu.parallel.trainer import PodTrainer
        from parameter_server_tpu.utils.checkpoint import dump_weights_text

        # the config's parallel section is the single source of truth for
        # the mesh shape (multi-host runs must set data_shards to a
        # multiple of num_processes; runtime.init validates)
        rt = runtime_mod.init(
            args.coordinator or None,
            args.num_processes,
            args.process_id,
            cfg=cfg,
        )
        trainer = PodTrainer(cfg, runtime=rt)
        if args.resume:
            if not args.ckpt_dir:
                raise SystemExit("--resume requires --ckpt_dir")
            trainer.load(args.ckpt_dir)
        pool_coord = None
        try:
            if args.pool_coordinator:
                if args.pool_serve and rt.process_index == 0:
                    from parameter_server_tpu.parallel.control import Coordinator

                    host, port = args.pool_coordinator.rsplit(":", 1)
                    pool_coord = Coordinator(host, int(port))
                out = dict(
                    trainer.train_files_dynamic(
                        cfg.data.files, args.pool_coordinator,
                        report_every=args.report_interval,
                    )
                    or {}
                )
            else:
                out = dict(
                    trainer.train_files(
                        cfg.data.files, report_every=args.report_interval
                    )
                    or {}
                )
            if args.ckpt_dir:
                trainer.save(args.ckpt_dir)
            if args.model_out and rt.process_index == 0:
                dump_weights_text(trainer.full_weights().ravel(), args.model_out)
            if cfg.data.val_files:
                ev = trainer.evaluate_files(cfg.data.val_files)
                out.update({f"val_{k}": v for k, v in ev.items()})
            out["process_index"] = rt.process_index
            out["mesh"] = {"data": rt.data_shards, "kv": rt.kv_shards}
        finally:
            # reached on errors too: a host that skipped the barrier would
            # park every other host in sync_global_devices forever, and an
            # unstopped Coordinator would leak its thread
            if args.pool_coordinator:
                rt.barrier("pool_shutdown")  # every host finished fetching
            if pool_coord is not None:
                pool_coord.stop()
        return out

    from parameter_server_tpu.models.linear import LinearMethod

    app = LinearMethod(cfg)
    if args.resume:
        if not args.ckpt_dir:
            raise SystemExit("--resume requires --ckpt_dir")
        app.load(args.ckpt_dir)
    last = (
        app.train_files(cfg.data.files, report_every=args.report_interval) or {}
    )  # reader applies cfg epochs
    if args.ckpt_dir:
        app.save(args.ckpt_dir)
    if args.model_out:
        app.dump_model(args.model_out)
    if cfg.data.val_files:
        from parameter_server_tpu.data.batch import eval_builder
        from parameter_server_tpu.data.reader import MinibatchReader

        ev = app.evaluate(
            MinibatchReader(cfg.data.val_files, cfg.data.format, eval_builder(cfg))
        )
        last = {**last, **{f"val_{k}": v for k, v in ev.items()}}
    return last


def _mesh_from_cfg(cfg: PSConfig):
    if cfg.parallel.data_shards * cfg.parallel.kv_shards > 1:
        from parameter_server_tpu.parallel import make_mesh

        return make_mesh(cfg.parallel.data_shards, cfg.parallel.kv_shards)
    return None


def _run_train_mf(cfg: PSConfig, args: argparse.Namespace) -> dict:
    """matrix_fac app dispatch (ref: App::Create on the MF config)."""
    import numpy as np

    from parameter_server_tpu.models.matrix_fac import MatrixFactorization

    m = cfg.mf
    app = MatrixFactorization(
        m.num_users, m.num_items, rank=m.rank, eta=m.eta, l2=m.l2,
        algo=m.algo, seed=cfg.seed, mesh=_mesh_from_cfg(cfg),
        push_mode=cfg.parallel.push_mode,
        max_delay=max(cfg.solver.max_delay, 0),
        steps_per_call=cfg.solver.steps_per_call,
    )
    rmse = app.train_files(
        cfg.data.files, batch_size=m.batch_size,
        epochs=max(1, cfg.solver.epochs), block_lines=m.block_lines,
        seed=cfg.seed,
    )
    out: dict = {"train_rmse": rmse, "rank": m.rank}
    if cfg.data.val_files:
        from parameter_server_tpu.models.matrix_fac import iter_rating_blocks

        sse, n = 0.0, 0
        for us, it, rt in iter_rating_blocks(cfg.data.val_files, m.block_lines):
            p = app.predict(us, it)
            sse += float(((p - rt) ** 2).sum())
            n += len(rt)
        if n == 0:
            # mirror train_files: a perfect 0.0 RMSE over zero parsed
            # triples must never be reported
            raise SystemExit(
                f"no rating triples parsed from val_files "
                f"{cfg.data.val_files}: expected 'user item rating' lines"
            )
        out["val_rmse"] = float(np.sqrt(sse / n))
        out["val_examples"] = n
    if args.model_out:
        U = np.asarray(app.user_up.weights(app.user_state))
        V = np.asarray(app.item_up.weights(app.item_state))
        np.savez(args.model_out, user_factors=U, item_factors=V)
        out["model_out"] = args.model_out
    return out


def _run_train_w2v(cfg: PSConfig, args: argparse.Namespace) -> dict:
    """word2vec app dispatch (ref: App::Create on the SGNS config)."""
    import numpy as np

    from parameter_server_tpu.models.word2vec import Word2Vec

    w = cfg.w2v
    app = Word2Vec(
        vocab_size=w.vocab_size, dim=w.dim, eta=w.eta,
        num_negatives=w.negatives, window=w.window, seed=cfg.seed,
        mesh=_mesh_from_cfg(cfg), max_delay=max(cfg.solver.max_delay, 0),
        push_mode=cfg.parallel.push_mode,
        steps_per_call=cfg.solver.steps_per_call,
    )
    # one call: train_files runs its epoch loop internally and pays the
    # vocab-counting pass ONCE, not once per epoch
    mean = app.train_files(
        cfg.data.files, batch_size=w.batch_size,
        epochs=max(1, cfg.solver.epochs),
        block_tokens=w.block_tokens, seed=cfg.seed,
    )
    out: dict = {"mean_loss": mean, "vocab_size": w.vocab_size, "dim": w.dim}
    if args.model_out:
        np.save(args.model_out, app.embeddings())
        out["model_out"] = args.model_out
    return out


def _run_train_wd(cfg: PSConfig, args: argparse.Namespace) -> dict:
    """wide_deep app dispatch (ref: App::Create on the W&D CTR config;
    BASELINE parity config "Wide-&-Deep CTR ... server-sharded
    embeddings"): streaming file-driven train over the same text formats
    as linear_method, optional (data, kv) mesh via [parallel]."""
    from parameter_server_tpu.data.batch import eval_builder, training_builder
    from parameter_server_tpu.models.wide_deep import WideDeep

    app = WideDeep.from_config(cfg, mesh=_mesh_from_cfg(cfg))
    last = app.train_files(
        cfg.data.files, cfg.data.format, training_builder(cfg),
        epochs=max(1, cfg.solver.epochs),
        report_every=args.report_interval,
    )
    out = dict(last or {})
    out.update({"emb_dim": cfg.wd.emb_dim, "hidden": list(cfg.wd.hidden)})
    if cfg.data.val_files:
        ev = app.evaluate_files(
            cfg.data.val_files, cfg.data.format, eval_builder(cfg)
        )
        out.update({f"val_{k}": v for k, v in ev.items()})
    if args.model_out:
        out["model_out"] = app.dump_model(args.model_out)
    return out


def run_convert(cfg: PSConfig, args: argparse.Namespace) -> dict:
    """Offline conversion (ref: the text2proto tool + SlotReader's
    parse-once cache): parse the config's text files once and populate the
    columnar block cache; later solver runs mmap it instead of re-parsing."""
    override_note = ""
    if args.cache_dir:
        if cfg.data.cache_dir != args.cache_dir:
            # a cache the training config doesn't point at is never read
            override_note = (
                "config data.cache_dir is "
                f"{cfg.data.cache_dir!r}; training will only use this "
                "cache if you point data.cache_dir at it"
            )
        cfg.data.cache_dir = args.cache_dir
    if not cfg.data.cache_dir:
        raise SystemExit("convert needs --cache_dir or config data.cache_dir")
    if not cfg.data.files:
        raise SystemExit("config data.files is empty")
    from pathlib import Path

    from parameter_server_tpu.data.blockcache import cached_column_blocks

    cb = cached_column_blocks(cfg)
    # the entry count comes from the cache sidecar: recomputing it would
    # page the whole (mmap'd) values array in just to rederive a stored stat
    meta = json.loads(
        (Path(cfg.data.cache_dir) / "meta.json").read_text()
    )
    out = {
        "cache_dir": cfg.data.cache_dir,
        "num_examples": cb.num_examples,
        "n_blocks": cb.n_blocks,
        "block_size": cb.block_size,
        "entries": meta["nnz"],
    }
    if override_note:
        out["warning"] = override_note
    return out


def run_evaluate(cfg: PSConfig, args: argparse.Namespace) -> dict:
    from parameter_server_tpu.models.evaluation import evaluate_model

    files = args.data if args.data else (cfg.data.val_files or cfg.data.files)
    if not files:
        raise SystemExit("no evaluation files (config val_files/files or --data)")
    if cfg.app == "wide_deep":
        # the W&D dump is an npz (wide + embedding + MLP), not the linear
        # apps' flat text vector
        from parameter_server_tpu.data.batch import eval_builder
        from parameter_server_tpu.models.wide_deep import evaluate_dump

        return evaluate_dump(
            args.model, files, cfg.data.format, eval_builder(cfg)
        )
    return evaluate_model(
        args.model,
        files,
        cfg.data.format,
        cfg.data.num_keys,
        batch_size=cfg.solver.minibatch,
        max_nnz_per_example=cfg.data.max_nnz_per_example,
    )


def run_backend(cfg: PSConfig, args: argparse.Namespace) -> dict:
    """One synthetic linear workload through the configured PSBackend
    (the ``[mesh]`` section picks the transport): the canonical
    ``train_linear`` loop that the backend-parity tests and the bench's
    ``backend`` cell also drive — so what this command measures is the
    production client path, not a demo fork of it."""
    import time

    import numpy as np

    from parameter_server_tpu.models.linear import updater_from_config
    from parameter_server_tpu.parallel.backend import (
        local_socket_backend,
        make_backend,
        train_linear,
    )
    from parameter_server_tpu.utils.metrics import wire_counters

    num_keys = cfg.data.num_keys
    n = max(args.examples // args.batch, 1) * args.batch
    rng = np.random.default_rng(cfg.seed or 7)
    w_true = rng.normal(size=num_keys - 1)
    kb = rng.integers(0, num_keys - 1, size=(n, args.nnz))
    logits = w_true[kb].sum(axis=1) / np.sqrt(args.nnz)
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(np.float64)

    if cfg.mesh.backend == "socket":
        backend = local_socket_backend(
            lambda: updater_from_config(cfg), num_keys,
            num_servers=args.servers, cfg=cfg,
        )
    else:
        backend = make_backend(cfg)
    pay0 = wire_counters.get("mesh_push_payload_bytes") + wire_counters.get(
        "wire_push_payload_bytes"
    )
    try:
        t0 = time.perf_counter()
        out = train_linear(backend, kb, y, args.batch)
        dt = time.perf_counter() - t0
        payload = (
            wire_counters.get("mesh_push_payload_bytes")
            + wire_counters.get("wire_push_payload_bytes")
            - pay0
        )
        return {
            "backend": cfg.mesh.backend,
            "auc": round(out["auc"], 4),
            "examples": out["examples"],
            "ex_per_sec": round(out["examples"] / dt, 1),
            "push_payload_mb": round(payload / 1e6, 3),
            "stats": backend.stats(),
        }
    finally:
        backend.close()  # owned loopback servers shut down with it


def run_stats(args: argparse.Namespace) -> dict:
    """The cluster dashboard (ref: the reference scheduler's printed
    table): query a live coordinator's ``telemetry`` command and print
    per-node rows + the merged per-command latency histograms."""
    from parameter_server_tpu.parallel.control import ControlClient
    from parameter_server_tpu.utils.metrics import (
        format_cluster_stats,
        hist_percentile,
    )

    ctl = ControlClient(args.scheduler, retries=5, reconnect_timeout_s=5.0)
    try:
        rep = ctl.telemetry()
    finally:
        ctl.close()
    print(format_cluster_stats(rep))
    merged = rep["merged"]
    return {
        "nodes": len(rep["nodes"]),
        "counters": merged["counters"],
        "latency_ms": {
            name: {
                "count": s.get("count", 0),
                "p50": round(hist_percentile(s, 0.5) * 1e3, 3),
                "p99": round(hist_percentile(s, 0.99) * 1e3, 3),
            }
            for name, s in merged["hists"].items()
        },
    }


def run_top(args: argparse.Namespace) -> int:
    """The auto-refreshing live dashboard (``cli top``): query the
    coordinator's ``telemetry`` command (windowed per-node series + SLO
    verdict) and render a frame every ``--interval``; ``--once`` prints
    a single frame for scripts and tests."""
    import time as time_mod

    from parameter_server_tpu.parallel.control import ControlClient
    from parameter_server_tpu.utils.slo import format_top

    ctl = ControlClient(args.scheduler, retries=5, reconnect_timeout_s=5.0)
    window = args.window or None
    try:
        while True:
            rep = ctl.telemetry(window_s=window)
            shown_window = (
                args.window
                or next(iter(rep.get("series", {}).values()), {}).get(
                    "window_s", 0.0
                )
            )
            if getattr(args, "json", False):
                # one-shot machine-readable frame: the same blocks the
                # dashboard renders, schema contract-tested in tier-1
                slo_rep = rep.get("slo") or {}
                print(json.dumps({
                    "window_s": float(shown_window or 0.0),
                    "nodes": rep.get("nodes") or {},
                    "series": rep.get("series") or {},
                    "health": slo_rep.get("health") or {},
                    "alerts": slo_rep.get("alerts") or [],
                    "audit": rep.get("audit") or {},
                }, default=float))
                return 0
            frame = format_top(rep, float(shown_window or 0.0))
            if args.once:
                print(frame)
                return 0
            # ANSI home+clear: the `top` idiom — repaint in place
            print("\x1b[2J\x1b[H" + frame, flush=True)
            time_mod.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        ctl.close()


def run_audit(args: argparse.Namespace) -> int:
    """The live audit plane's viewer (``cli audit``): one-shot summary
    (exit 1 when violations exist, so drills and CI gate on it) or a
    follow loop printing each NEW violation as the coordinator's
    streaming monitors raise it."""
    import time as time_mod

    from parameter_server_tpu.parallel.control import ControlClient
    from parameter_server_tpu.utils.slo import format_audit, format_violation

    ctl = ControlClient(args.scheduler, retries=5, reconnect_timeout_s=5.0)
    try:
        rep = ctl.audit(recent=args.recent)
        if args.json:
            print(json.dumps(rep, default=float))
            return 1 if rep.get("total") else 0
        if args.once:
            print(format_audit(rep))
            return 1 if rep.get("total") else 0
        # follow mode: poll, print only what is new since the last frame
        print(format_audit(rep))
        seen = int(rep.get("total") or 0)
        while True:
            time_mod.sleep(args.interval)
            rep = ctl.audit(recent=args.recent)
            total = int(rep.get("total") or 0)
            if total > seen:
                fresh = (rep.get("recent") or [])[-(total - seen):]
                for v in fresh:
                    print(format_violation(v).strip(), flush=True)
                seen = total
    except KeyboardInterrupt:
        return 0
    finally:
        ctl.close()


def run_whylate(args: argparse.Namespace) -> int:
    """Tail-latency forensics (``cli whylate``): critical-path
    attribution over a trace/blackbox capture dir or a live cluster,
    with optional per-segment budget gating (tiered exits: 0 within
    budget, 2 over budget, 1 past the hard factor — the pslint
    ``--baseline`` convention, so CI fails on WHICH segment
    regressed)."""
    from parameter_server_tpu.analysis import critpath

    if bool(args.dir) == bool(args.scheduler):
        raise SystemExit(
            "whylate needs exactly one input: a capture dir or "
            "--scheduler host:port"
        )
    if args.scheduler and (args.baseline or args.update_baseline):
        # live records carry only the slowest-K segment splits, not the
        # per-segment p99 population a budget gates on: silently passing
        # every budget (or rewriting the committed baseline to empty)
        # would be a CI gate that never fires
        raise SystemExit(
            "whylate --baseline/--update-baseline gate offline captures; "
            "point them at a trace/blackbox dir, not --scheduler"
        )
    if args.update_baseline and not args.baseline:
        raise SystemExit(
            "whylate --update-baseline needs --baseline FILE (the file "
            "to rewrite) — without it nothing would be written"
        )
    if args.scheduler:
        from parameter_server_tpu.parallel.control import ControlClient

        ctl = ControlClient(
            args.scheduler, retries=5, reconnect_timeout_s=5.0
        )
        try:
            summary = critpath.analyze_live(ctl.telemetry(), top=args.top)
        finally:
            ctl.close()
    else:
        summary = critpath.analyze_dir(args.dir, top=args.top)
    findings: list[dict] = []
    rc = 0
    if args.baseline and args.update_baseline:
        critpath.update_baseline(summary, args.baseline)
    elif args.baseline:
        if not summary.get("ops"):
            # an empty capture cannot PASS a budget gate: zero stitched
            # ops means the export (or the dir argument) broke, and
            # exiting 0 here would silently disarm the CI contract
            raise SystemExit(
                f"whylate --baseline: no stitchable ops found in "
                f"{args.dir!r} — cannot gate an empty capture"
            )
        findings = critpath.check_baseline(
            summary, critpath.load_baseline(args.baseline)
        )
        rc = critpath.baseline_exit_code(findings)
    if args.json:
        print(json.dumps(
            {**summary, "baseline_findings": findings}, default=float
        ))
        return rc
    print(critpath.render_report(summary, top=args.top))
    for f in findings:
        print(
            f"BUDGET {f['tier'].upper()}: {f['cmd']}.{f['segment']} "
            f"p99 {f['p99_ms']}ms > budget {f['budget_ms']}ms"
        )
    if args.baseline and not args.update_baseline and not findings:
        print("all segment budgets met")
    return rc


def run_ranges(args: argparse.Namespace) -> int:
    """The freshness dashboard (``cli ranges``): per-range traffic and
    realized data-age matrix from the coordinator's ``telemetry``
    command, auto-refreshing like ``cli top``; ``--once``/``--json``
    print a single frame for scripts and tests."""
    import time as time_mod

    from parameter_server_tpu.parallel.control import ControlClient
    from parameter_server_tpu.utils.slo import format_ranges, ranges_view

    ctl = ControlClient(args.scheduler, retries=5, reconnect_timeout_s=5.0)
    window = args.window or None
    try:
        while True:
            rep = ctl.telemetry(window_s=window)
            shown_window = (
                args.window
                or next(iter(rep.get("series", {}).values()), {}).get(
                    "window_s", 0.0
                )
            )
            if args.json:
                print(json.dumps(
                    ranges_view(rep, float(shown_window or 0.0)),
                    default=float,
                ))
                return 0
            frame = format_ranges(rep, float(shown_window or 0.0))
            if args.once:
                print(frame)
                return 0
            print("\x1b[2J\x1b[H" + frame, flush=True)
            time_mod.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        ctl.close()


def run_verify(args: argparse.Namespace) -> int:
    """The verification meta-command (``cli verify``): run every armed
    analysis stage and fold their exit codes into one tiered verdict —
    1 when ANY stage failed hard (lint findings, model-checker
    violation, audit violations, whylate hard regression), else 2 when
    any stage was merely over budget (the whylate/pslint soft tier),
    else 0. One command, one exit code: what CI and the bench README
    workflow gate on."""
    from parameter_server_tpu.analysis.__main__ import (
        check_main,
        main as lint_main,
    )

    stages: list[dict] = []

    def _stage(name: str, fn) -> None:
        print(f"[verify] {name} ...", flush=True)
        try:
            rc = int(fn() or 0)
        except SystemExit as e:  # argparse/guard exits inside a stage
            rc = e.code if isinstance(e.code, int) else 1
        except Exception as e:  # a crashed stage is a hard failure,
            # not a crashed verify: the remaining stages still run
            print(f"[verify] {name} crashed: {e}", flush=True)
            rc = 1
        stages.append({"stage": name, "exit": rc})
        print(
            f"[verify] {name}: " + ("ok" if rc == 0 else f"exit {rc}"),
            flush=True,
        )

    lint_argv: list[str] = []
    if args.lint_baseline:
        lint_argv += ["--baseline", args.lint_baseline]
    if args.lint_changed_only:
        lint_argv += ["--changed-only", args.lint_changed_only]
    _stage("lint", lambda: lint_main(lint_argv))
    _stage(
        "check",
        lambda: check_main(["--max-states", str(args.max_states)]),
    )
    if args.scheduler:
        au = argparse.Namespace(
            scheduler=args.scheduler, interval=2.0, once=True,
            json=False, recent=20,
        )
        _stage("audit", lambda: run_audit(au))
    if args.whylate_dir:
        wl = argparse.Namespace(
            dir=args.whylate_dir, scheduler="", top=5, json=False,
            baseline=args.whylate_baseline, update_baseline=False,
        )
        _stage("whylate", lambda: run_whylate(wl))
    hard = [s["stage"] for s in stages if s["exit"] not in (0, 2)]
    soft = [s["stage"] for s in stages if s["exit"] == 2]
    rc = 1 if hard else (2 if soft else 0)
    verdict = (
        f"FAILED ({', '.join(hard)})" if hard
        else f"over budget ({', '.join(soft)})" if soft
        else "all stages clean"
    )
    if args.json:
        print(json.dumps({
            "stages": stages, "hard": hard, "soft": soft, "exit": rc,
        }))
    else:
        print(f"[verify] verdict: {verdict} — exit {rc}")
    return rc


def main(argv: list[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.cmd == "lint":
        # no config file: lint analyzes the installed package source
        from parameter_server_tpu.analysis.__main__ import main as lint_main

        lint_argv: list[str] = []
        for c in args.checker or ():
            lint_argv += ["--checker", c]
        if args.json:
            lint_argv.append("--json")
        if args.baseline:
            lint_argv += ["--baseline", args.baseline]
        if args.update_baseline:
            lint_argv.append("--update-baseline")
        if args.changed_only:
            lint_argv += ["--changed-only", args.changed_only]
        return lint_main(lint_argv)
    if args.cmd == "check":
        # no config file: the model checker verifies protocol SPECS and
        # their conformance to the installed package source
        from parameter_server_tpu.analysis.__main__ import check_main

        check_argv: list[str] = []
        for s in args.spec or ():
            check_argv += ["--spec", s]
        check_argv += ["--max-states", str(args.max_states)]
        if args.probe_seeds:
            check_argv += ["--probe-seeds", str(args.probe_seeds)]
        if args.bug:
            check_argv += ["--bug", args.bug]
        if args.no_conformance:
            check_argv.append("--no-conformance")
        if args.json:
            check_argv.append("--json")
        return check_main(check_argv)
    if args.cmd == "explore":
        from pathlib import Path

        from parameter_server_tpu.analysis import explorer

        repo_root = Path(__file__).resolve().parent.parent
        corpus = args.corpus or str(
            repo_root / "tests" / "sched_corpus.json"
        )
        # the corpus keys on the node id STRING and the explorer-armed
        # tier-1 run looks seeds up by the canonical repo-relative
        # spelling — normalize absolute/cwd-relative paths to it, or a
        # recorded seed would never be replayed
        file_part, sep, rest = args.test.partition("::")
        fp = Path(file_part)
        if fp.exists():
            try:
                canon = fp.resolve().relative_to(repo_root).as_posix()
            except ValueError:
                canon = file_part  # outside the repo: keep as typed
            if canon != file_part:
                args.test = canon + sep + rest
                print(f"explore: node id normalized to {args.test}")

        def _note(seed: int, passed: bool) -> None:
            print(
                f"explore: seed {seed} "
                + ("passed" if passed else "FAILED — replayable")
            )

        search_err: Exception | None = None
        try:
            failing = explorer.search_seeds(
                args.test, budget=args.budget,
                start_seed=args.start_seed,
                on_result=_note, timeout_s=args.timeout,
            )
        except explorer.SearchError as e:
            # record/report what the budget found BEFORE surfacing the
            # infra break — a long search must not lose its finds
            failing, search_err = e.failing, e
        if failing and not args.no_record:
            explorer.record_failing_seeds(corpus, args.test, failing)
            print(f"explore: {len(failing)} failing seed(s) recorded "
                  f"in {corpus}")
        for seed in failing:
            print(f"  replay: PS_SCHED={seed} python -m pytest "
                  f"{args.test}")
        print(
            f"explore: {len(failing)}/{args.budget} seed(s) broke "
            f"{args.test}"
        )
        if search_err is not None:
            print(f"explore: search aborted — {search_err}")
            return 1
        # always 0: finding a failing seed is the SUCCESSFUL outcome of
        # an exploration budget, and the recorded corpus (replayed by
        # the explorer-armed tier-1 run) is the durable gate — CI gates
        # on that replay, not on this search's exit code
        return 0
    if args.cmd == "stats":
        # no config file: stats only needs a live coordinator address
        print(json.dumps(run_stats(args), default=float))
        return 0
    if args.cmd == "top":
        # no config file: the dashboard reads the live coordinator
        return run_top(args)
    if args.cmd == "ranges":
        # no config file: the freshness dashboard reads the live
        # coordinator (range boundaries ride the series names)
        return run_ranges(args)
    if args.cmd == "verify":
        # no config file: every chained stage is itself config-free
        return run_verify(args)
    if args.cmd == "audit":
        # no config file: the sentinel reads the live coordinator
        return run_audit(args)
    if args.cmd == "whylate":
        # no config file: forensics read a capture dir or the live
        # coordinator's piggybacked slow-op records
        return run_whylate(args)
    if args.cmd == "postmortem":
        # no config file: a postmortem works from the dumps alone
        from parameter_server_tpu.utils.postmortem import postmortem

        out = postmortem(args.dir, trace_out=args.trace_out, tail=args.tail)
        print(out.pop("report"))
        print(json.dumps(out, default=float))
        # anomalies => nonzero, so a soak harness can gate on the exit
        return 1 if out["anomalies"] else 0
    cfg = load_config(args.app_file)
    if getattr(args, "trace_dir", ""):
        # flag wins over both the config and the ambient env; run_node /
        # PodTrainer re-arm with a role-specific process name from cfg
        cfg.trace.trace_dir = args.trace_dir
    msrv = roller = None
    armed_prof = False
    if args.cmd == "train":
        if cfg.trace.trace_dir:
            from parameter_server_tpu.utils import trace

            trace.configure(
                cfg.trace.trace_dir, capacity=cfg.trace.capacity,
                process_name="train",
                sample=cfg.trace.sample,
                tail=cfg.trace.tail,
                tail_k=cfg.trace.tail_k,
                tail_limbo=cfg.trace.tail_limbo,
            )
        # live-ops arming for the single-process train path (spawned
        # node roles arm in run_node with role-rank names): continuous
        # profiler from [profile]/PS_PROFILE; OpenMetrics endpoint from
        # [timeseries], with a Roller thread feeding the local ring at
        # heartbeat cadence (no beats feed it here) so /healthz serves
        # a live windowed summary
        from parameter_server_tpu.utils import profiler, timeseries

        hz = cfg.profile.hz if cfg.profile.hz > 0 else profiler.env_hz()
        if hz > 0:
            profiler.configure(
                hz, top_n=cfg.profile.top_n,
                max_depth=cfg.profile.max_depth,
                dump_dir=cfg.profile.dump_dir, process_name="train",
            )
            armed_prof = True
        # same port resolution as run_node: the config wins, then the
        # inherited PS_METRICS_PORT (the documented env arming path)
        import os as os_mod

        mport = cfg.timeseries.metrics_port or int(
            os_mod.environ.get(timeseries.METRICS_PORT_ENV, "0") or 0
        )
        if mport > 0:
            timeseries.reset_local_ring(cfg.timeseries.capacity)
            msrv = timeseries.start_metrics_server(
                mport, process_name="train",
                host=cfg.timeseries.metrics_host,
                window_s=cfg.timeseries.window_s,
            )
            roller = timeseries.Roller(cfg.fault.heartbeat_interval_s)
    try:
        if args.cmd == "train":
            out = run_train(cfg, args)
        elif args.cmd == "backend":
            out = run_backend(cfg, args)
        elif args.cmd == "evaluate":
            out = run_evaluate(cfg, args)
        elif args.cmd == "convert":
            out = run_convert(cfg, args)
        elif args.cmd == "node":
            from parameter_server_tpu.parallel.multislice import run_node

            if args.fault_plan:
                # flag wins over both the ambient env and the config
                # file; the cfg field carries it into every RpcServer
                # this node builds
                cfg.fault.fault_plan = args.fault_plan
                cfg.fault.fault_seed = args.fault_seed
            out = run_node(
                cfg, args.role, args.rank, args.scheduler,
                args.num_servers, args.num_workers, args.model_out,
                bind_host=args.bind_host, advertise_host=args.advertise_host,
                ckpt_dir=args.ckpt_dir,
            )
            if out is None:  # servers/workers exit silently; scheduler reports
                return 0
        else:
            from parameter_server_tpu.parallel.multislice import launch_local

            out = launch_local(
                args.app_file, args.num_servers, args.num_workers,
                args.model_out,
                fault_plan=args.fault_plan, fault_seed=args.fault_seed,
                trace_dir=args.trace_dir, blackbox_dir=args.blackbox_dir,
            )
    finally:
        # an in-process caller (tests) must not leak the HTTP server,
        # the roll thread or a still-sampling profiler past main()
        # (disarming the profiler also writes its configured dumps)
        if roller is not None:
            roller.close()
        if msrv is not None:
            msrv.close()
        if armed_prof:
            from parameter_server_tpu.utils import profiler

            profiler.configure(0)
    print(json.dumps(out, default=float))
    return 0


if __name__ == "__main__":
    sys.exit(main())
