"""Fixed-point quantization codec with stochastic (unbiased) rounding.

Reference analog: src/filter/fixing_float.h — quantize floats into n-byte
fixed point with randomized rounding and per-array min/max scaling, applied
symmetrically on send/receive. Here encode/decode are jit-able functions
meant to wrap **DCN** (cross-slice) gradient collectives: encode before the
wire, decode after, e.g.

    enc = codec.encode(key, grads)            # int8/int16 + scale
    agg = lax.psum(enc.q.astype(f32), 'dcn')  # cheap wire format
    grads = codec.decode_sum(enc.scale, agg)

Stochastic rounding keeps E[decode(encode(x))] == x, which is what makes
low-bit gradient pushes safe for FTRL/AdaGrad (the reference's motivation
for randomized rounding)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple

import jax
import jax.numpy as jnp


class Encoded(NamedTuple):
    q: jax.Array  # integer payload
    lo: jax.Array  # per-array min (scalar)
    scale: jax.Array  # (hi - lo) / levels (scalar)


@dataclass(frozen=True)
class FixedPointCodec:
    """num_bytes in {1, 2}: int8 or int16 payloads (ref: FilterConfig
    num_bytes)."""

    num_bytes: int = 1

    @property
    def _levels(self) -> int:
        return (1 << (8 * self.num_bytes)) - 1

    @property
    def _dtype(self):
        return jnp.int8 if self.num_bytes == 1 else jnp.int16

    def __post_init__(self) -> None:
        if self.num_bytes not in (1, 2):
            raise ValueError("num_bytes must be 1 or 2")

    def encode(self, key: jax.Array, x: jax.Array) -> Encoded:
        """Quantize to [lo, hi] with stochastic rounding. ``key`` is a JAX
        PRNG key (the randomness source for unbiased rounding)."""
        lo = jnp.min(x)
        hi = jnp.max(x)
        scale = jnp.maximum(hi - lo, 1e-30) / self._levels
        t = (x - lo) / scale  # in [0, levels]
        floor = jnp.floor(t)
        frac = t - floor
        up = jax.random.uniform(key, x.shape) < frac
        q = floor + up.astype(t.dtype)
        zero = self._levels // 2
        return Encoded(
            (q - zero).astype(self._dtype),
            lo.astype(jnp.float32),
            scale.astype(jnp.float32),
        )

    def encode_fast(self, seed: int, x: jax.Array) -> Encoded:
        """Device-path encode: Pallas kernel with the TPU hardware PRNG
        (~50x the threefry jnp path at 64 MB on v5e). Falls back to
        ``encode`` off-TPU."""
        from parameter_server_tpu.ops.pallas_kernels import (
            quantize_stochastic_pallas,
            tpu_available,
        )

        if tpu_available():
            q, lo, scale = quantize_stochastic_pallas(
                seed, x, num_bytes=self.num_bytes
            )
            return Encoded(q, lo, scale)
        return self.encode(jax.random.key(seed), x)

    def decode(self, e: Encoded) -> jax.Array:
        zero = self._levels // 2
        return (e.q.astype(jnp.float32) + zero) * e.scale + e.lo

    def bytes_saved(self, x: jax.Array) -> float:
        """Wire-size ratio vs float32 (ref: the Postoffice per-filter byte
        counters reporting compression savings)."""
        return 1.0 - self.num_bytes / 4.0
