"""Client-side versioned key-VALUE cache for the serving plane.

Reference analog: src/filter/key_caching.h cached the key LISTS of a
message so repeats send a signature instead of the keys. This module
generalizes that idea to the values themselves for read-mostly (serving)
traffic, the way the TeraByte-scale ads framework (arXiv 2201.05500)
splits one parameter plane into a training path and a cached serving
path: every pull reply carries the shard's RCU publish *version*, the
client caches the decoded rows under the key-set signature, and a later
pull of the same keys is served

- **locally** while the entry is younger than the TTL (zero wire bytes),
- **by revalidation** once the TTL lapses: an ``if_newer=<version>``
  pull that comes back ``not_modified`` re-arms the TTL without moving
  a single row byte,
- **from the wire** only when the server's version actually moved.

Invalidation is EXACT: a push through the owning handle invalidates
every cached entry whose key set intersects the pushed keys (an
inverted key -> signatures index makes that one dict probe per pushed
key), so a client can never read its own write stale. Staleness against
OTHER writers is bounded by ``ttl_ms`` — and by ``max_stale_ms`` as a
hard ceiling when the server sheds revalidations under load.

One cache serves a MULTI-SHARD frontend (ISSUE 8, the PR-7 carry-over):
entries are namespaced by shard ``rank``. Keys on this wire are
range-RELATIVE, so two shards produce identical signatures (and
identical key ints) for different rows — a rank-blind shared cache
would serve shard A's rows for shard B's pull and cross-invalidate on
push. Handles pass ``(rank, sig)`` composite signatures and their rank
to ``put``/``invalidate_keys``; the inverted index keys by
``(rank, key)``.

Thread safety: one lock around the map + inverted index. Nothing
blocking ever runs under it (lookups, puts and invalidations are dict
and small-array operations); the wire round trip always happens with
the lock released, so a slow revalidation never parks concurrent local
hits.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

import numpy as np

from parameter_server_tpu.utils.metrics import race_track, wire_counters


class CacheEntry:
    """One cached key set: the decoded float32 rows, the server version
    they were read at, and the two clocks bounding how long they may be
    served (``expires_at``: the soft TTL, re-armed by revalidation;
    ``filled_at``: when the server last CONFIRMED this version, the
    anchor of the hard ``max_stale`` ceiling).

    Freshness plane (ISSUE 17): ``age0_us`` is the server-measured data
    age (µs since the RCU publish) at the moment the entry was filled
    or last revalidated — the reply's ``_age_us`` echo. A cached serve
    at monotonic time ``now`` hands out rows whose realized age is
    ``age0_us + (now - filled_at)``: the cross-machine term is measured
    on the SERVER's clock (skew-free) and only the local dwell time is
    measured here."""

    __slots__ = (
        "keys", "values", "version", "filled_at", "expires_at", "rank",
        "age0_us",
    )

    def __init__(
        self, keys: np.ndarray, values: np.ndarray, version: int,
        filled_at: float, expires_at: float, rank: int = 0,
        age0_us: float = 0.0,
    ):
        self.keys = keys
        self.values = values
        self.version = version
        self.filled_at = filled_at
        self.expires_at = expires_at
        self.rank = rank  # shard namespace of the inverted-index rows
        self.age0_us = float(age0_us)

    def age_us(self, now: float | None = None) -> float:
        """Realized age (µs) of these rows if served at ``now``."""
        now = time.monotonic() if now is None else now
        return self.age0_us + max(now - self.filled_at, 0.0) * 1e6


class ClientKeyCache:
    """LRU of key-set signature -> :class:`CacheEntry` with an exact
    inverted index ((rank, key) -> signatures) driving push
    invalidation. ``sig`` is any hashable — a multi-shard frontend's
    handles pass ``(rank, digest)`` composites so one shared cache never
    collides range-relative keys across shards."""

    def __init__(
        self, cap: int = 1024, ttl_s: float = 0.05, max_stale_s: float = 0.5
    ):
        self.cap = max(1, int(cap))
        self.ttl_s = float(ttl_s)
        self.max_stale_s = float(max_stale_s)
        self._lock = threading.Lock()
        self._d: OrderedDict = OrderedDict()  # sig -> CacheEntry
        self._by_key: dict[tuple[int, int], set] = {}  # (rank, key) -> sigs
        # refresh coalescing: signatures with a revalidation in flight.
        # While one caller refreshes a stale entry, concurrent pulls of
        # the same keys serve the (within-max_stale) cached rows instead
        # of issuing duplicate wire refreshes — ONE refresh per stale
        # entry per expiry, however many threads share the cache.
        self._refreshing: set = set()
        # invalidation generation: bumped by EVERY invalidate_keys call
        # (even one that dropped nothing — the racing pull's entry may
        # not be indexed yet). A put whose pull was issued before a
        # later invalidation must lose, or a reply in flight across a
        # concurrent push would re-install pre-push rows and this
        # frontend would read its own write stale.
        self._gen = 0
        # lockset race witness (PS_RACE_WITNESS=1): the generation is
        # read by every pull path and bumped by every push invalidation
        # across a frontend's threads — all under _lock, or the
        # read-your-writes reasoning above is fiction
        race_track(self, ("_gen",), "ClientKeyCache")

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    @property
    def gen(self) -> int:
        """Current invalidation generation — capture BEFORE issuing a
        wire pull and hand to :meth:`put` so an install can never race
        past an invalidation (read-your-writes across threads)."""
        with self._lock:
            return self._gen

    # -- reads -------------------------------------------------------------

    def lookup(self, sig) -> CacheEntry | None:
        """The entry for ``sig`` (LRU-touched), or None. The caller
        decides freshness via :meth:`fresh` / :meth:`can_shed` — lookup
        never drops a stale entry, because a stale entry still carries
        the version that makes an if_newer revalidation cheap."""
        with self._lock:
            ent = self._d.get(sig)
            if ent is not None:
                self._d.move_to_end(sig)
            return ent

    def fresh(self, ent: CacheEntry, now: float | None = None) -> bool:
        """Young enough to serve locally without any wire traffic."""
        return (time.monotonic() if now is None else now) < ent.expires_at

    def can_shed(self, ent: CacheEntry, now: float | None = None) -> bool:
        """Young enough to keep serving if the server sheds the
        revalidation (the hard staleness ceiling): the client advertises
        ``shed_ok`` on the wire only while this holds, so an overloaded
        server can never stretch a client past ``max_stale_s``."""
        now = time.monotonic() if now is None else now
        return now - ent.filled_at <= self.max_stale_s

    def begin_refresh(self, sig) -> bool:
        """Claim the (single-flight) refresh of a stale entry: True when
        this caller owns it and must go to the wire — and MUST call
        :meth:`end_refresh` on every settle path; False when a refresh
        is already in flight (serve the bounded-stale entry instead)."""
        with self._lock:
            if sig in self._refreshing:
                return False
            self._refreshing.add(sig)
            return True

    def end_refresh(self, sig) -> None:
        with self._lock:
            self._refreshing.discard(sig)

    # -- writes ------------------------------------------------------------

    @staticmethod
    def _sig_rank(sig) -> int | None:
        """The rank a ``(rank, digest)`` composite signature carries
        (None for a plain signature)."""
        if isinstance(sig, tuple) and sig and isinstance(sig[0], int):
            return sig[0]
        return None

    def put(
        self, sig, keys: np.ndarray, values: np.ndarray, version: int,
        now: float | None = None, as_of: int | None = None,
        rank: int | None = None, age_us: float | None = None,
    ) -> CacheEntry | None:
        """Install freshly pulled rows (replacing any older entry).
        ``as_of`` is the :attr:`gen` captured when the pull was ISSUED:
        if any invalidation ran since, the install is skipped (returns
        None) — the rows may predate a push that already invalidated
        this key set, and installing them would serve a stale
        read-your-write. Conservative by design (any invalidation
        cancels any in-flight install): pushes are rare on the
        read-mostly tier this cache serves, so a lost install costs one
        refresh, while a falsely kept one would cost correctness."""
        # index namespace: derived from a composite sig, or given
        # explicitly — and the two must AGREE, or a push's rank-scoped
        # invalidation would silently miss this entry and serve stale
        # pre-push rows for up to the ttl/max_stale bound
        srank = self._sig_rank(sig)
        if rank is None:
            rank = srank if srank is not None else 0
        elif srank is not None and srank != rank:
            raise ValueError(
                f"put(sig={sig!r}, rank={rank}): the composite sig "
                f"carries rank {srank} — entry and inverted index would "
                "disagree and exact invalidation would break"
            )
        now = time.monotonic() if now is None else now
        keys = np.array(keys, copy=True)
        values = np.array(values, copy=True)  # own both: callers may reuse
        ent = CacheEntry(
            keys, values, int(version), now, now + self.ttl_s, int(rank),
            age0_us=float(age_us or 0.0),
        )
        with self._lock:
            if as_of is not None and as_of != self._gen:
                wire_counters.inc("serve_cache_put_races")
                return None
            old = self._d.pop(sig, None)
            if old is not None:
                self._unindex(sig, old)
            self._d[sig] = ent
            for k in keys.tolist():
                self._by_key.setdefault((ent.rank, k), set()).add(sig)
            while len(self._d) > self.cap:
                esig, evicted = self._d.popitem(last=False)
                self._unindex(esig, evicted)
        return ent

    def revalidated(
        self, sig, version: int, now: float | None = None,
        age_us: float | None = None,
    ) -> None:
        """A ``not_modified`` reply confirmed the entry's version is
        still current: re-arm BOTH clocks — the data is as fresh as the
        round trip that just verified it. ``age_us`` re-anchors the
        realized-age clock off the reply's server-measured ``_age_us``
        echo; absent (pre-freshness server), the age keeps accumulating
        from the previous anchor — an unknown age must grow, never
        reset to zero on a reply that moved no rows."""
        now = time.monotonic() if now is None else now
        with self._lock:
            ent = self._d.get(sig)
            if ent is None:
                return
            ent.version = int(version)
            ent.age0_us = (
                float(age_us) if age_us is not None else ent.age_us(now)
            )
            ent.filled_at = now
            ent.expires_at = now + self.ttl_s
        wire_counters.inc("serve_cache_validates")

    def shed_backoff(self, sig, retry_after_s: float) -> None:
        """The server shed this entry's revalidation: keep serving the
        (still within-max_stale) entry for ``retry_after_s`` before
        asking again — but never past the hard ceiling, so a stream of
        shed replies cannot stretch staleness beyond ``max_stale_s``."""
        with self._lock:
            ent = self._d.get(sig)
            if ent is None:
                return
            ent.expires_at = min(
                time.monotonic() + retry_after_s,
                ent.filled_at + self.max_stale_s,
            )

    def invalidate_keys(self, keys: np.ndarray, rank: int = 0) -> int:
        """Drop every entry of shard ``rank`` whose key set intersects
        ``keys`` (exact push invalidation: one inverted-index probe per
        pushed key); returns how many entries died. Rank-scoped: keys
        are range-relative, so shard A's push must never evict shard
        B's rows that happen to share local key ints."""
        klist = np.asarray(keys).tolist()  # outside the lock: asarray may
        # sync a device buffer, and the lock must stay nanosecond-scale
        rank = int(rank)
        with self._lock:
            self._gen += 1  # even when nothing cached matches: an
            # in-flight pull of exactly these keys has no entry to drop,
            # and its put must still lose to this invalidation
            doomed: set = set()
            for k in klist:
                sigs = self._by_key.get((rank, k))
                if sigs:
                    doomed.update(sigs)
            for sig in doomed:
                ent = self._d.pop(sig, None)
                if ent is not None:
                    self._unindex(sig, ent)
        if doomed:
            wire_counters.inc("serve_cache_invalidations", len(doomed))
        return len(doomed)

    def _unindex(self, sig, ent: CacheEntry) -> None:
        """Caller holds ``self._lock``."""
        for k in ent.keys.tolist():
            sigs = self._by_key.get((ent.rank, k))
            if sigs is not None:
                sigs.discard(sig)
                if not sigs:
                    del self._by_key[(ent.rank, k)]
