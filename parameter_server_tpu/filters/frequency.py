"""Tail-feature frequency filter (count-min sketch).

Reference analog: src/parameter/frequency_filter.h — only admit keys seen
at least k times, because at 10^9+ raw CTR features the tail is noise and
would blow up the model. Host-side ingest component: feed raw (pre-hash)
keys as they stream by; ask ``admit`` before including them in batches."""

from __future__ import annotations

import numpy as np

from parameter_server_tpu.utils.hashing import splitmix64

_SEEDS = np.array([0x9E37, 0x85EB, 0xC2B2, 0x27D4], dtype=np.uint64)


class CountMinSketch:
    """Vectorized count-min over uint64 keys with ``depth`` hash rows."""

    def __init__(self, width: int = 1 << 20, depth: int = 4, dtype=np.uint32):
        if depth > len(_SEEDS):
            raise ValueError(f"depth <= {len(_SEEDS)}")
        self.width = int(width)
        self.depth = int(depth)
        self.table = np.zeros((depth, self.width), dtype=dtype)

    def _rows(self, keys: np.ndarray) -> np.ndarray:
        k = np.asarray(keys, dtype=np.uint64)
        out = np.empty((self.depth, len(k)), dtype=np.int64)
        for d in range(self.depth):
            with np.errstate(over="ignore"):
                out[d] = (splitmix64(k ^ _SEEDS[d]) % np.uint64(self.width)).astype(
                    np.int64
                )
        return out

    def add(self, keys: np.ndarray) -> None:
        idx = self._rows(keys)
        for d in range(self.depth):
            np.add.at(self.table[d], idx[d], 1)

    def count(self, keys: np.ndarray) -> np.ndarray:
        """Estimated counts (never under-estimates)."""
        idx = self._rows(keys)
        ests = np.stack([self.table[d][idx[d]] for d in range(self.depth)])
        return ests.min(axis=0)

    def admit(self, keys: np.ndarray, min_count: int) -> np.ndarray:
        """Bool mask of keys seen at least ``min_count`` times (ref: the
        filter's admission threshold)."""
        return self.count(keys) >= min_count

    def state_dict(self) -> dict:
        return {"table": self.table}

    def load_state_dict(self, d: dict) -> None:
        t = np.asarray(d["table"])
        if t.shape != self.table.shape:
            raise ValueError(f"sketch shape {t.shape} != {self.table.shape}")
        self.table = t.copy()
