"""Per-segment-scale int8/int16 gradient quantizer — the wire codec.

Generalizes :mod:`filters/fixed_point` (one min/max affine scale per whole
array, the reference's fixing_float filter) into the production-grade form
EQuARX-style gradient exchange uses: the payload is cut into fixed-length
SEGMENTS and each segment carries its own symmetric scale, so one outlier
coordinate no longer destroys the resolution of the other few hundred
thousand (the reference's per-array scaling loses ~all mantissa bits on
heavy-tailed FTRL gradients; per-segment scaling bounds the blast radius
to ``seg`` coordinates).

Design points, each load-bearing for the wire tier:

- **Symmetric zero.** ``q = round(x / scale)`` with a per-segment scale of
  ``max|x| / qmax`` maps 0.0 to exactly 0 — the KV store's pad-row
  invariant (pad slots carry zero gradient, row 0 absorbs zero updates)
  survives quantization bit-exactly. The affine (lo + scale*q) form of
  ``FixedPointCodec`` does not guarantee this.
- **Stochastic rounding.** ``E[decode(encode(x))] == x``: the server's
  batched apply sees an unbiased gradient, which is what keeps
  FTRL/AdaGrad trajectories statistically unchanged. The residual of each
  *realized* rounding still lands in the client's error-feedback
  accumulator (parallel/multislice.ServerHandle), so the bias AND the
  variance are both compensated across steps.
- **Wire shape.** ``encode`` returns ``q`` trimmed to the input's true
  length (the zero-padding needed for the segment reshape never rides the
  wire) plus one float32 scale per segment — at the default ``seg=256``
  the scale overhead is 4/256 ≈ 1.6%, so int8 transport is a ~3.8x
  payload reduction vs float32. Both arrays ride the binary header's
  array-descriptor table like any other payload chunk (dtype + shape),
  and the adaptive compression layer already skips int8/int16 chunks.
- **No blocking calls.** The numpy fast path below runs on wire threads
  (possibly under the handle's residual lock); it deliberately avoids
  every primitive pslint's blocking-under-lock checker flags.

The jitted jax twins (:func:`quantize_segments` / :func:`dequantize_
segments`) are the device-path form (SPMD quantized push mode, tests
assert numpy/jax parity); the host wire path uses the numpy
implementation because per-push lengths are arbitrary (per-range key
slices) and must not trigger a recompile per fresh shape.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

#: smallest representable scale: a segment of exact zeros must decode to
#: exact zeros without a divide-by-zero on the encode side
_TINY = 1e-30


def _qmax(num_bytes: int) -> int:
    return (1 << (8 * num_bytes - 1)) - 1  # 127 / 32767


@functools.lru_cache(maxsize=None)
def _jit_cores(num_bytes: int, seg: int):
    """Build (encode, decode) jitted jax cores for one codec geometry.
    Inputs are pre-padded to a segment multiple; lazy so importing this
    module never initializes jax."""
    import jax
    import jax.numpy as jnp

    qmax = _qmax(num_bytes)
    dtype = jnp.int8 if num_bytes == 1 else jnp.int16

    @jax.jit
    def enc(key, x):  # x: (nseg * seg,) f32, zero-padded
        xs = x.reshape(-1, seg)
        scale = jnp.maximum(jnp.max(jnp.abs(xs), axis=1) / qmax, _TINY)
        t = xs / scale[:, None]
        floor = jnp.floor(t)
        frac = t - floor
        up = jax.random.uniform(key, t.shape) < frac
        q = jnp.clip(floor + up, -qmax, qmax).astype(dtype)
        return q.reshape(-1), scale.astype(jnp.float32)

    @jax.jit
    def dec(q, scale):
        qs = q.reshape(-1, seg).astype(jnp.float32)
        return (qs * scale[:, None]).reshape(-1)

    return enc, dec


def quantize_segments(key, x, num_bytes: int = 1, seg: int = 256):
    """Jitted device-path encode: ``x`` (flat f32, length a multiple of
    ``seg``) -> (q, per-segment scales). ``key`` is a jax PRNG key."""
    return _jit_cores(num_bytes, seg)[0](key, x)


def dequantize_segments(q, scale, num_bytes: int = 1, seg: int = 256):
    """Jitted device-path decode (inverse of :func:`quantize_segments`)."""
    return _jit_cores(num_bytes, seg)[1](q, scale)


def dequantize_flat(q, scale, seg: int = 256):
    """Trace-safe decode of an arbitrary-length payload (the host codec's
    trimmed wire shape): re-pad ``q`` to the segment multiple, scale per
    segment, trim. Shapes are static under jit, so this inlines into a
    larger program — the mesh backend's quantized push dequantizes with
    it INSIDE the sharded update, after the int8 payload crossed the
    collective boundary (EQuARX: quantize before the exchange,
    dequantize after)."""
    import jax.numpy as jnp

    n = int(q.shape[0])
    flat = q.astype(jnp.float32)
    pad = (-n) % seg
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, jnp.float32)])
    out = (flat.reshape(-1, seg) * scale[:, None].astype(jnp.float32))
    return out.reshape(-1)[:n]


@dataclass(frozen=True)
class SegmentQuantizer:
    """The host wire codec: int8/int16 payload + one f32 scale per ``seg``
    coordinates, stochastic (unbiased) rounding on encode.

    ``encode`` / ``decode`` are numpy-vectorized and shape-flexible
    (arbitrary input lengths; the pad needed for the segment reshape is
    internal and never serialized)."""

    num_bytes: int = 1
    seg: int = 256

    def __post_init__(self) -> None:
        if self.num_bytes not in (1, 2):
            raise ValueError("num_bytes must be 1 or 2")
        if self.seg < 1:
            raise ValueError("seg must be >= 1")

    @property
    def qmax(self) -> int:
        return _qmax(self.num_bytes)

    @property
    def dtype(self):
        return np.int8 if self.num_bytes == 1 else np.int16

    def _padded(self, x: np.ndarray) -> np.ndarray:
        flat = x.astype(np.float32, copy=False).reshape(-1)
        pad = (-len(flat)) % self.seg
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, np.float32)])
        return flat

    def encode(
        self, seed: int, x: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """Quantize ``x`` -> (q: int8/int16 (n,), scales: f32 (nseg,)).
        ``seed`` feeds the stochastic-rounding RNG; distinct pushes must
        use distinct seeds (the handle's atomic counter does)."""
        n = int(np.size(x))
        xs = self._padded(x).reshape(-1, self.seg)
        scale = np.abs(xs).max(axis=1) / self.qmax
        np.maximum(scale, _TINY, out=scale)
        t = xs / scale[:, None]
        floor = np.floor(t)
        frac = t - floor
        up = np.random.default_rng(seed).random(t.shape, dtype=np.float32)
        q = floor + (up < frac)
        np.clip(q, -self.qmax, self.qmax, out=q)
        return (
            q.reshape(-1)[:n].astype(self.dtype),
            scale.astype(np.float32),
        )

    def encode_nearest(self, x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Deterministic round-to-nearest encode (no seed) — the PULL
        side's form: weight reads have no error-feedback loop to redeem
        stochastic rounding's unbiasedness, so nearest halves the
        worst-case error and keeps repeated reads of one unchanged
        snapshot bit-identical (cacheable, diffable, reproducible)."""
        n = int(np.size(x))
        xs = self._padded(x).reshape(-1, self.seg)
        scale = np.abs(xs).max(axis=1) / self.qmax
        np.maximum(scale, _TINY, out=scale)
        q = np.rint(xs / scale[:, None])
        np.clip(q, -self.qmax, self.qmax, out=q)
        return (
            q.reshape(-1)[:n].astype(self.dtype),
            scale.astype(np.float32),
        )

    def decode(self, q: np.ndarray, scale: np.ndarray) -> np.ndarray:
        """Dequantize -> flat float32 of ``q``'s length (the encode-side
        pad was trimmed before the wire; re-pad, scale, trim again)."""
        n = int(np.size(q))
        flat = q.astype(np.float32, copy=False).reshape(-1)
        pad = (-n) % self.seg
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, np.float32)])
        out = flat.reshape(-1, self.seg) * scale[:, None].astype(np.float32)
        return out.reshape(-1)[:n]

    def wire_bytes(self, n: int) -> int:
        """Payload bytes for an ``n``-coordinate push (q + scales)."""
        nseg = -(-n // self.seg)
        return n * self.num_bytes + 4 * nseg
