"""Bandwidth filters (reference analog: src/filter/).

The reference's filter pipeline (key caching, snappy compression,
fixed-point float truncation) exists because ZeroMQ point-to-point traffic
is its scarce resource. On a TPU pod:

- **key caching** survives as the data layer's static batch layouts: the
  unique-key plan of a batch is device-resident and reused; no keys move
  per step at all on the ICI path.
- **compression / fixed-point** matter again on the **DCN** (cross-slice)
  path: quantized gradient collectives. ``FixedPointCodec`` is that codec,
  with the reference's randomized (unbiased) rounding.
- snappy-style byte compression has no collective analog; omitted by
  design (recorded in PARITY.md).
- on the cross-process wire the key-caching idea generalizes to the
  VALUES themselves for read-mostly serving traffic: ``keycache.py``
  holds a versioned client-side key->rows cache with TTL/revalidation
  and exact push invalidation (the serving plane, ISSUE 7).
"""

from parameter_server_tpu.filters.fixed_point import FixedPointCodec  # noqa: F401
from parameter_server_tpu.filters.frequency import CountMinSketch  # noqa: F401
from parameter_server_tpu.filters.keycache import ClientKeyCache  # noqa: F401
from parameter_server_tpu.filters.quant import SegmentQuantizer  # noqa: F401
