"""The sharded key->value model store (reference analog: src/parameter/).

The reference's server side is a hash-map of Entry structs updated on Push
and read on Pull (src/parameter/kv_map.h + per-app entries); the worker side
is KVVector (src/parameter/kv_vector.h). Here both collapse into:

- ``state``: a pytree of dense arrays over the hashed key space, sharded
  over the ``kv`` mesh axis (the "servers"),
- ``pull(state, idx)``: gather rows (all-gather/psum over ``kv`` in SPMD),
- ``push(state, idx, grad)``: apply a server-side updater to the touched
  rows (reduce over ``data``, scatter into the ``kv`` shards).
"""

from parameter_server_tpu.kv.store import KVStore  # noqa: F401
from parameter_server_tpu.kv.updaters import (  # noqa: F401
    Adagrad,
    Ftrl,
    Sgd,
    make_updater,
)
