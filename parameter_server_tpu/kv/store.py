"""The KV store core: functional pull/push over dense state tables.

Reference analog: src/parameter/shared_parameter.h (the Push/Pull protocol)
+ src/parameter/kv_vector.h (worker-side match) + the server KV map. In the
TPU re-expression there is no wire: ``pull`` is a row gather and ``push``
is gather -> updater -> scatter over the touched rows only (never the full
table, mirroring the reference's touch-only server updates).

Invariants (enforced by the data layer's localizer, ref: Localizer in
src/app/linear_method/localizer.h):
  - ``idx`` passed to ``push`` contains each real key at most once; padding
    slots carry ``idx == PAD_KEY (0)`` and ``grad == 0``. Duplicate real
    keys must be pre-aggregated (segment-summed) by the caller: the updater
    computes one *delta* per (key, grad) pair, so double-counting a key
    would apply the nonlinear update twice.
  - Row 0 is the pad row: it absorbs zero-gradient updates and is excluded
    from dumps and nnz counts.

The SPMD (multi-device) pull/push live in parameter_server_tpu.parallel —
same updater objects, rows gathered from the local ``kv`` shard instead.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from parameter_server_tpu.kv.updaters import Updater

State = dict[str, jax.Array]


@functools.partial(jax.jit, static_argnums=0)
def pull(updater: Updater, state: State, idx: jax.Array) -> jax.Array:
    """Gather weights for (unique, padded) key indices: (U,) -> (U, vdim)."""
    rows = {k: jnp.take(v, idx, axis=0) for k, v in state.items()}
    return updater.weights(rows)


@functools.partial(jax.jit, static_argnums=0)
def push(updater: Updater, state: State, idx: jax.Array, grad: jax.Array) -> State:
    """Apply the server updater to the touched rows; returns new state.

    grad: (U, vdim) pre-aggregated gradient aligned with ``idx``.
    """
    rows = {k: jnp.take(v, idx, axis=0) for k, v in state.items()}
    deltas = updater.delta(rows, grad)
    return {k: state[k].at[idx].add(deltas[k]) for k in state}


@functools.partial(jax.jit, static_argnums=0)
def materialize_weights(updater: Updater, state: State) -> jax.Array:
    """Full (K, vdim) weight table (FTRL: lazily derived from z, n)."""
    return updater.weights(state)


class KVStore:
    """Stateful convenience wrapper an app holds (one sharded "server group").

    The reference app holds a KVVector bound to a SharedParameter customer id;
    here the app holds a KVStore bound to an updater + state pytree.
    """

    def __init__(
        self,
        updater: Updater,
        num_keys: int,
        vdim: int = 1,
        dtype: Any = jnp.float32,
    ):
        self.updater = updater
        self.num_keys = int(num_keys)
        self.vdim = int(vdim)
        self.state: State = updater.init(self.num_keys, self.vdim, dtype)

    def pull(self, idx: jax.Array) -> jax.Array:
        return pull(self.updater, self.state, idx)

    def push(self, idx: jax.Array, grad: jax.Array) -> None:
        self.state = push(self.updater, self.state, idx, grad)

    def weights(self) -> jax.Array:
        return materialize_weights(self.updater, self.state)

    def nnz(self, tol: float = 0.0) -> int:
        """Count of nonzero weights excluding the pad row (ref: nnz(w) in
        the scheduler's progress table)."""
        w = np.asarray(self.weights())[1:]
        return int((np.abs(w) > tol).sum())
