"""The KV store core: functional pull/push over dense state tables.

Reference analog: src/parameter/shared_parameter.h (the Push/Pull protocol)
+ src/parameter/kv_vector.h (worker-side match) + the server KV map. In the
TPU re-expression there is no wire: ``pull`` is a row gather and ``push``
is gather -> updater -> scatter over the touched rows only (never the full
table, mirroring the reference's touch-only server updates).

Invariants (enforced by the data layer's localizer, ref: Localizer in
src/app/linear_method/localizer.h):
  - ``idx`` passed to ``push`` contains each real key at most once; padding
    slots carry ``idx == PAD_KEY (0)`` and ``grad == 0``. Duplicate real
    keys must be pre-aggregated (segment-summed) by the caller: the updater
    computes one *delta* per (key, grad) pair, so double-counting a key
    would apply the nonlinear update twice.
  - Row 0 is the pad row: it absorbs zero-gradient updates and is excluded
    from dumps and nnz counts.

The SPMD (multi-device) pull/push live in parameter_server_tpu.parallel —
same updater objects, rows gathered from the local ``kv`` shard instead.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from parameter_server_tpu.kv.updaters import Updater

State = dict[str, jax.Array]


def pad_state_rows(state: State, num_rows: int) -> State:
    """Zero-extend every table of ``state`` on axis 0 up to ``num_rows``
    (identity when already there). Pad rows obey the store's pad-row
    invariant — exactly zero, never pushed (the data layer only emits
    keys below the real ``num_keys``), so they are invisible to pulls,
    dumps and nnz counts. This is what lets the sharded tiers accept an
    arbitrary ``num_keys`` on any kv-axis size: the table is padded up
    to the next axis multiple and the extra rows stay inert."""
    have = next(iter(state.values())).shape[0]
    if have == num_rows:
        return state
    if have > num_rows:
        raise ValueError(f"cannot pad {have} rows down to {num_rows}")
    return {
        k: jnp.concatenate(
            [v, jnp.zeros((num_rows - have, *v.shape[1:]), v.dtype)], axis=0
        )
        for k, v in state.items()
    }


@functools.partial(jax.jit, static_argnums=0)
def pull(updater: Updater, state: State, idx: jax.Array) -> jax.Array:
    """Gather weights for (unique, padded) key indices: (U,) -> (U, vdim)."""
    rows = {k: jnp.take(v, idx, axis=0) for k, v in state.items()}
    return updater.weights(rows)


@functools.partial(jax.jit, static_argnums=0)
def push(updater: Updater, state: State, idx: jax.Array, grad: jax.Array) -> State:
    """Apply the server updater to the touched rows; returns new state.

    grad: (U, vdim) pre-aggregated gradient aligned with ``idx``.
    """
    rows = {k: jnp.take(v, idx, axis=0) for k, v in state.items()}
    deltas = updater.delta(rows, grad)
    return {k: state[k].at[idx].add(deltas[k]) for k in state}


@functools.partial(jax.jit, static_argnums=0)
def materialize_weights(updater: Updater, state: State) -> jax.Array:
    """Full (K, vdim) weight table (FTRL: lazily derived from z, n)."""
    return updater.weights(state)


def coalesce_pushes(
    idx_list: list[np.ndarray],
    grad_list: list[np.ndarray],
    pad_to_pow2: bool = False,
) -> tuple[np.ndarray, np.ndarray]:
    """Pre-aggregate several concurrent pushes into ONE (idx, grad) pair
    honoring the store invariant: each real key at most once, duplicate
    keys segment-summed. This is the host-side half of the server's
    batched apply engine — N pushes (possibly from N different clients,
    with overlapping key sets) collapse into one updater apply, and a
    nonlinear updater (FTRL) sees each gradient contribution exactly once
    in the aggregate, matching the paper's aggregated server updates.

    ``pad_to_pow2`` pads the union with PAD_KEY (0) rows carrying zero
    gradient — the same slot semantics the data layer's localizer
    guarantees (row 0 absorbs zero-gradient updates). Coalesced unions
    otherwise have a DIFFERENT length every batch, and on the eager
    server tier each fresh shape re-dispatches/compiles the whole updater
    chain — the pow-2 bucket pins batches to a handful of shapes (the
    ``bucket_nnz`` idiom applied to the server's apply path).

    ``grad_list`` entries are (U_i, vdim) (or (U_i,), normalized here);
    returns (unique_idx, (U, vdim) summed grads) as numpy host arrays.
    """
    if len(idx_list) == 1:
        uniq = np.asarray(idx_list[0])
        summed = np.asarray(grad_list[0]).reshape(len(uniq), -1)
        # a single push carries no duplicates (the localizer contract) —
        # pass through, padding only if asked
    else:
        idx = np.concatenate([np.asarray(i) for i in idx_list])
        g = np.concatenate(
            [
                np.asarray(x).reshape(len(i), -1)
                for i, x in zip(idx_list, grad_list)
            ]
        )
        uniq, inv = np.unique(idx, return_inverse=True)
        summed = np.zeros((len(uniq), g.shape[1]), dtype=g.dtype)
        np.add.at(summed, inv, g)
    if pad_to_pow2:
        u = len(uniq)
        cap = 1 << max(u - 1, 0).bit_length()
        if cap > u:
            uniq = np.concatenate([uniq, np.zeros(cap - u, uniq.dtype)])
            summed = np.concatenate(
                [summed, np.zeros((cap - u, summed.shape[1]), summed.dtype)]
            )
    return uniq, summed


def push_multi(
    updater: Updater,
    state: State,
    idx_list: list[np.ndarray],
    grad_list: list[np.ndarray],
    pad_to_pow2: bool = False,
) -> State:
    """Batched multi-push: coalesce N pushes (segment-summing duplicate
    keys across them) and apply the updater ONCE over the union of
    touched rows — one dispatch instead of N. Semantics are the paper's
    server-side aggregation: deltas are computed from the pre-batch rows
    and the summed gradient.

    This is the single-program (KVStore) batched entry point. The wire
    tier's ``ShardServer`` apply engine composes the SAME two primitives
    (``coalesce_pushes`` + ``push``) directly, because its durable push
    ledger and RCU publish must share one critical section with the
    apply — semantics changes to batching belong in those primitives,
    where both paths pick them up."""
    idx, grad = coalesce_pushes(idx_list, grad_list, pad_to_pow2)
    return push(updater, state, jnp.asarray(idx), jnp.asarray(grad))


class KVStore:
    """Stateful convenience wrapper an app holds (one sharded "server group").

    The reference app holds a KVVector bound to a SharedParameter customer id;
    here the app holds a KVStore bound to an updater + state pytree.
    """

    def __init__(
        self,
        updater: Updater,
        num_keys: int,
        vdim: int = 1,
        dtype: Any = jnp.float32,
    ):
        self.updater = updater
        self.num_keys = int(num_keys)
        self.vdim = int(vdim)
        self.state: State = updater.init(self.num_keys, self.vdim, dtype)

    def pull(self, idx: jax.Array) -> jax.Array:
        return pull(self.updater, self.state, idx)

    def push(self, idx: jax.Array, grad: jax.Array) -> None:
        self.state = push(self.updater, self.state, idx, grad)

    def push_multi(
        self, idx_list: list[np.ndarray], grad_list: list[np.ndarray]
    ) -> None:
        """Apply N pushes as one coalesced, segment-summed update (the
        batched server apply; see module-level ``push_multi``)."""
        self.state = push_multi(self.updater, self.state, idx_list, grad_list)

    def weights(self) -> jax.Array:
        return materialize_weights(self.updater, self.state)

    def nnz(self, tol: float = 0.0) -> int:
        """Count of nonzero weights excluding the pad row (ref: nnz(w) in
        the scheduler's progress table)."""
        w = np.asarray(self.weights())[1:]
        return int((np.abs(w) > tol).sum())
