"""Server-side updaters: SGD, AdaGrad, FTRL-proximal.

Reference analog: the Entry types applied by the server KV store on push —
SGD/AdaGrad/FTRL entries in src/app/linear_method/async_sgd.h (server side)
and the proximal operator in src/app/linear_method/penalty.h.

Each updater is a frozen dataclass of hyperparameters with three pure
methods over *row slices* (the touched keys' state), so the same code runs:
  - single-device (rows gathered by ``jnp.take``),
  - SPMD (rows gathered from the local ``kv`` shard under ``shard_map``),
  - inside a Pallas kernel (the math is elementwise over rows).

State layout per table (vdim = values per key, reference's "value segments"):
  sgd:     {"w": (K, vdim)}
  adagrad: {"w": (K, vdim), "n": (K, vdim)}
  ftrl:    {"z": (K, vdim), "n": (K, vdim)}   -- w is DERIVED lazily
FTRL stores no w: the weight is materialized from (z, n) on pull, which is
exactly the reference's lazy L1 sparsification (untouched keys stay exactly
zero without ever being written).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Protocol

import jax.numpy as jnp

Rows = dict[str, Any]  # name -> (U, vdim) array slice of touched keys


class Updater(Protocol):
    """All updaters express their step as an exact additive ``delta`` so the
    sharded push can be a deterministic scatter-ADD (duplicate/out-of-range
    slots contribute zero) rather than a row write. ``apply`` == rows + delta.
    """

    name: str

    def init(self, num_keys: int, vdim: int, dtype: Any) -> Rows: ...

    def delta(self, rows: Rows, grad: Any) -> Rows: ...

    def weights(self, rows: Rows) -> Any: ...


def apply_update(updater: "Updater", rows: Rows, grad: Any) -> Rows:
    d = updater.delta(rows, grad)
    return {k: rows[k] + d[k] for k in rows}


@dataclass(frozen=True)
class Sgd:
    """Plain SGD with optional L2: w -= eta * (g + l2 * w)."""

    eta: float = 0.1
    lambda_l2: float = 0.0
    name: str = "sgd"

    def init(self, num_keys: int, vdim: int = 1, dtype: Any = jnp.float32) -> Rows:
        return {"w": jnp.zeros((num_keys, vdim), dtype)}

    def delta(self, rows: Rows, grad: Any) -> Rows:
        return {"w": -self.eta * (grad + self.lambda_l2 * rows["w"])}

    def weights(self, rows: Rows) -> Any:
        return rows["w"]


@dataclass(frozen=True)
class Adagrad:
    """AdaGrad: n += g^2; w -= eta * g / (sqrt(n) + eps)."""

    eta: float = 0.1
    eps: float = 1e-8
    lambda_l2: float = 0.0
    name: str = "adagrad"

    def init(self, num_keys: int, vdim: int = 1, dtype: Any = jnp.float32) -> Rows:
        # distinct buffers: donation requires state leaves not to alias
        return {
            "w": jnp.zeros((num_keys, vdim), dtype),
            "n": jnp.zeros((num_keys, vdim), dtype),
        }

    def delta(self, rows: Rows, grad: Any) -> Rows:
        g = grad + self.lambda_l2 * rows["w"]
        dn = g * g
        n = rows["n"] + dn
        return {"w": -self.eta * g / (jnp.sqrt(n) + self.eps), "n": dn}

    def weights(self, rows: Rows) -> Any:
        return rows["w"]


@dataclass(frozen=True)
class Ftrl:
    """FTRL-proximal (McMahan et al.), the reference's flagship updater.

    Per touched key (ref: FTRLEntry in async_sgd.h server side):
        w      = prox(z, n)                      # current weight, derived
        sigma  = (sqrt(n + g^2) - sqrt(n)) / alpha
        z     += g - sigma * w
        n     += g^2
    and the lazy weight:
        w(z,n) = 0                                   if |z| <= lambda_l1
               = -(z - sign(z)*lambda_l1)
                 / ((beta + sqrt(n))/alpha + lambda_l2)   otherwise
    """

    alpha: float = 0.1
    beta: float = 1.0
    lambda_l1: float = 1.0
    lambda_l2: float = 0.0
    use_pallas: bool = False  # fuse the delta in one Pallas VMEM pass on TPU
    name: str = "ftrl"

    def init(self, num_keys: int, vdim: int = 1, dtype: Any = jnp.float32) -> Rows:
        return {
            "z": jnp.zeros((num_keys, vdim), dtype),
            "n": jnp.zeros((num_keys, vdim), dtype),
        }

    def delta(self, rows: Rows, grad: Any) -> Rows:
        if self.use_pallas:
            from parameter_server_tpu.ops.pallas_kernels import (
                ftrl_delta_pallas,
                tpu_available,
            )

            if tpu_available():

                dz, dn = ftrl_delta_pallas(
                    rows["z"], rows["n"], grad,
                    alpha=self.alpha, beta=self.beta,
                    l1=self.lambda_l1, l2=self.lambda_l2,
                )
                return {"z": dz, "n": dn}
        n = rows["n"]
        w = self.weights(rows)
        n_new = n + grad * grad
        sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / self.alpha
        return {"z": grad - sigma * w, "n": grad * grad}

    def weights(self, rows: Rows) -> Any:
        z, n = rows["z"], rows["n"]
        shrunk = jnp.sign(z) * jnp.maximum(jnp.abs(z) - self.lambda_l1, 0.0)
        denom = (self.beta + jnp.sqrt(n)) / self.alpha + self.lambda_l2
        return -shrunk / denom


def make_updater(algo: str, **kw: Any) -> Updater:
    """Factory by config name (ref: solver/penalty fields of the app proto)."""
    table = {"sgd": Sgd, "adagrad": Adagrad, "ftrl": Ftrl}
    if algo not in table:
        raise ValueError(f"unknown updater '{algo}'; known: {sorted(table)}")
    cls = table[algo]
    valid = {f.name for f in dataclasses.fields(cls)} - {"name"}
    bad = set(kw) - valid
    if bad:
        raise ValueError(f"unknown {algo} hyperparameter(s) {sorted(bad)}")
    return cls(**kw)
