"""Continuous sampling profiler: folded stacks from ``sys._current_frames``.

Reference analog: none — the reference profiled with gperftools offline.
This is the live half (ISSUE 13): one daemon thread per armed process
samples every thread's Python stack at ``hz`` (wall-clock profiling:
blocked threads show their blocking frames, which is exactly what a
"where is the apply thread stuck" question needs), folds each stack into
a ``frame;frame;frame`` string and aggregates counts — the flamegraph
input format. Two export paths:

- ``dump()`` writes ``prof-<name>-<pid>.collapsed`` (one ``stack count``
  line per folded stack — flamegraph.pl / speedscope / inferno input)
  and a Perfetto-loadable ``.trace.json`` built by replaying the bounded
  sample ring into per-thread flame-chart spans (consecutive samples
  sharing a frame prefix keep that frame's span open) through the shared
  ``trace.write_chrome_trace`` exporter;
- the **top-N hot stacks** ride the heartbeat telemetry piggyback
  (``metrics.telemetry_snapshot`` resolves this module through
  ``sys.modules`` — the ``race_track`` pattern, so an unarmed process
  never imports or pays for the profiler).

Disarmed discipline (the flightrec contract, restated): the module-level
``top_stacks`` is an **identity-pinned no-op** while disarmed (tests
assert ``top_stacks is _noop_top_stacks``), no sampler thread exists,
and arming is ``PS_PROFILE=<hz>`` env at import (spawned children
inherit it for free — the PS_FAULT_PLAN pattern) or ``[profile]``
config via ``configure()``.

Frame identity uses ``co_firstlineno`` (the def line), not the executing
line — otherwise every bytecode position would be its own stack and the
fold would never aggregate.
"""

from __future__ import annotations

import atexit
import os
import sys
import threading
import time
from collections import deque
from typing import Any

from parameter_server_tpu.utils import flightrec
from parameter_server_tpu.utils.metrics import wire_counters

PROFILE_ENV = "PS_PROFILE"
PROFILE_DIR_ENV = "PS_PROFILE_DIR"

DEFAULT_HZ = 29.0  # offset from round frequencies: never beats against
#                    a 10/100 Hz periodic workload and aliases nothing
#: folded-stack table bound: a pathological workload (generated code)
#: cannot grow the fold without bound — past this, new stacks collapse
#: into one "<other>" bucket (the KeyHeatSketch saturation discipline)
MAX_STACKS = 4096
#: bounded sample ring for the Perfetto flame-chart export (~2 minutes
#: of 29 Hz samples across a handful of threads)
MAX_SAMPLES = 8192


def _frame_label(frame) -> str:
    code = frame.f_code
    fn = code.co_filename
    short = "/".join(fn.replace("\\", "/").split("/")[-2:])
    return f"{code.co_name} ({short}:{code.co_firstlineno})"


class SamplingProfiler:
    """The sampler thread + folded aggregation (see module docstring)."""

    def __init__(
        self,
        hz: float = DEFAULT_HZ,
        top_n: int = 5,
        max_depth: int = 24,
        dump_dir: str = "",
        process_name: str = "",
    ):
        self.hz = float(hz) if hz > 0 else DEFAULT_HZ
        self.top_n = int(top_n)
        self.max_depth = int(max_depth)
        self.dump_dir = dump_dir
        self.process_name = process_name or f"proc-{os.getpid()}"
        self._folded: dict[str, int] = {}
        self._samples: deque[tuple[float, int, tuple[str, ...]]] = deque(
            maxlen=MAX_SAMPLES
        )
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.samples = 0  # completed sampling passes (watchdog-style probe)

    # -- sampling ---------------------------------------------------------

    def start(self) -> "SamplingProfiler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="ps-profiler"
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        interval = 1.0 / self.hz
        me = threading.get_ident()
        while not self._stop.wait(interval):
            self.sample_once(exclude_ident=me)

    def sample_once(self, exclude_ident: int | None = None) -> int:
        """One sampling pass over every thread's current frame (tests
        drive this directly for determinism); returns stacks folded."""
        ts = time.time()
        frames = sys._current_frames()
        folded: list[tuple[int, tuple[str, ...]]] = []
        for ident, frame in frames.items():
            if ident == exclude_ident:
                continue  # the sampler observing itself is pure noise
            stack: list[str] = []
            f = frame
            while f is not None and len(stack) < self.max_depth:
                stack.append(_frame_label(f))
                f = f.f_back
            stack.reverse()  # root-first: the folded/flamegraph order
            folded.append((ident, tuple(stack)))
        with self._lock:
            for ident, stack in folded:
                key = ";".join(stack)
                if key not in self._folded and len(self._folded) >= MAX_STACKS:
                    key = "<other>"
                self._folded[key] = self._folded.get(key, 0) + 1
                self._samples.append((ts, ident, stack))
            self.samples += 1
        wire_counters.inc("prof_samples", len(folded))
        return len(folded)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # -- reads ------------------------------------------------------------

    def top_stacks(self, n: int | None = None) -> list[dict[str, Any]]:
        """The hottest folded stacks, heaviest first — the heartbeat
        piggyback block (``[{"s": folded, "n": count}, ...]``)."""
        with self._lock:
            items = sorted(self._folded.items(), key=lambda kv: -kv[1])
        return [
            {"s": s, "n": c} for s, c in items[: (n or self.top_n)]
        ]

    def folded(self) -> dict[str, int]:
        with self._lock:
            return dict(self._folded)

    # -- export -----------------------------------------------------------

    def to_chrome_events(self) -> list[dict[str, Any]]:
        """Replay the sample ring into per-thread flame-chart spans:
        a frame's span stays open while consecutive samples keep it at
        the same depth (standard sampled-profile reconstruction); gaps
        longer than ~2 sample intervals close everything."""
        with self._lock:
            samples = list(self._samples)
        # "thread", not "tid": in this package tid means TRACE id — the
        # OS thread id only surfaces in the Chrome-format "tid" field
        by_thread: dict[int, list[tuple[float, tuple[str, ...]]]] = {}
        for ts, thread, stack in samples:
            by_thread.setdefault(thread, []).append((ts, stack))
        pid = os.getpid()
        dt = 1.0 / self.hz
        events: list[dict[str, Any]] = []

        for thread, thread_samples in by_thread.items():
            thread_samples.sort(key=lambda x: x[0])
            open_frames: list[tuple[str, float]] = []  # (label, start_ts)

            def close_from(depth: int, end_ts: float, thread=thread) -> None:
                while len(open_frames) > depth:
                    label, t0 = open_frames.pop()
                    events.append({
                        "name": label,
                        "cat": "prof",
                        "ph": "X",
                        "ts": t0 * 1e6,
                        "dur": max((end_ts - t0) * 1e6, 1.0),
                        "pid": pid,
                        "tid": thread,
                    })

            prev_ts: float | None = None
            for ts, stack in thread_samples:
                if prev_ts is not None and ts - prev_ts > 2.5 * dt:
                    close_from(0, prev_ts + dt)  # sampling gap: restart
                common = 0
                for (label, _), cur in zip(open_frames, stack):
                    if label != cur:
                        break
                    common += 1
                close_from(common, ts)
                for label in stack[common:]:
                    open_frames.append((label, ts))
                prev_ts = ts
            if prev_ts is not None:
                close_from(0, prev_ts + dt)
        return events

    def dump(self, out_dir: str | None = None) -> dict[str, str] | None:
        """Write the collapsed + Perfetto exports; returns their paths
        (None when nothing was sampled or no dir is configured)."""
        d = out_dir or self.dump_dir
        if not d or not self.folded():
            return None
        os.makedirs(d, exist_ok=True)
        base = os.path.join(
            d, f"prof-{self.process_name}-{os.getpid()}"
        )
        collapsed = base + ".collapsed"
        tmp = collapsed + ".tmp"
        with open(tmp, "w") as f:
            for stack, count in sorted(self.folded().items()):
                f.write(f"{stack} {count}\n")
        os.replace(tmp, collapsed)
        from parameter_server_tpu.utils.trace import write_chrome_trace

        trace_path = write_chrome_trace(
            self.to_chrome_events(), base + ".trace.json",
            process_names={os.getpid(): f"prof:{self.process_name}"},
        )
        wire_counters.inc("prof_dumps")
        flightrec.record(
            "prof.dump", stacks=len(self.folded()), samples=self.samples,
        )
        return {"collapsed": collapsed, "trace": trace_path}


# -- module-level arming (the flightrec discipline) -------------------------

_profiler: SamplingProfiler | None = None


def _noop_top_stacks(n: int | None = None) -> None:
    """Disarmed path: identity-pinned (tests assert ``top_stacks is
    _noop_top_stacks``) — the telemetry piggyback hook costs one call
    returning None on every unprofiled process."""
    return None


def _live_top_stacks(n: int | None = None) -> list[dict[str, Any]] | None:
    p = _profiler
    return p.top_stacks(n) if p is not None else None


#: the piggyback entry point ``metrics.telemetry_snapshot`` resolves via
#: sys.modules; rebound by configure() between the no-op and live paths
top_stacks = _noop_top_stacks


def enabled() -> bool:
    return _profiler is not None


def current() -> SamplingProfiler | None:
    return _profiler


def _atexit_dump() -> None:  # pragma: no cover - interpreter teardown
    try:
        p = _profiler
        if p is not None:
            p.stop()
            p.dump()
    except Exception:  # noqa: BLE001 — teardown must not mask exit
        pass


_atexit_armed = False


def configure(
    hz: float,
    top_n: int = 5,
    max_depth: int = 24,
    dump_dir: str = "",
    process_name: str = "",
) -> SamplingProfiler | None:
    """Arm (hz > 0) or disarm (hz <= 0) the process profiler, rebinding
    the module-level ``top_stacks`` between the live and the
    identity-pinned no-op paths. Re-arming stops the previous sampler
    and starts fresh (configure at process start, like the tracer)."""
    global _profiler, top_stacks, _atexit_armed
    if _profiler is not None:
        _profiler.stop()
        if _profiler.dump_dir:
            _profiler.dump()
        _profiler = None
        top_stacks = _noop_top_stacks
    if hz is None or hz <= 0:
        return None
    _profiler = SamplingProfiler(
        hz=hz, top_n=top_n, max_depth=max_depth,
        dump_dir=dump_dir, process_name=process_name,
    ).start()
    top_stacks = _live_top_stacks
    if not _atexit_armed:
        atexit.register(_atexit_dump)
        _atexit_armed = True
    return _profiler


def env_hz(value: str | None = None) -> float:
    """Parse the ``PS_PROFILE`` arming value: off for ``""``/``0``/
    ``off``/``false``, the default rate for ``1``/``true``/``on``, a
    number for an explicit Hz."""
    if value is None:
        value = os.environ.get(PROFILE_ENV, "")
    v = (value or "").strip().lower()
    if v in ("", "0", "off", "false", "no"):
        return 0.0
    if v in ("1", "on", "true", "yes"):
        return DEFAULT_HZ
    try:
        hz = float(v)
    except ValueError:
        return DEFAULT_HZ
    return hz if hz > 0 else 0.0


# env-armed at import so spawned children need no plumbing (the
# PS_FAULT_PLAN pattern); run_node re-configures with a role-rank name
if env_hz() > 0:
    configure(
        env_hz(), dump_dir=os.environ.get(PROFILE_DIR_ENV, "")
    )
