"""Version-compat shims over the moving parts of the jax API surface.

The SPMD tier targets the modern public API (``jax.shard_map`` with the
``check_vma`` kwarg); older interpreters in the 0.4.x line ship the same
machinery as ``jax.experimental.shard_map.shard_map`` with the kwarg named
``check_rep``. The host control plane must not become uninstallable over a
spelling drift in an API we use identically either way, so every in-repo
``shard_map`` import routes through here.
"""

from __future__ import annotations

import inspect

try:  # modern public API (jax >= ~0.6)
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # 0.4.x line: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

_HAS_VMA = "check_vma" in inspect.signature(_shard_map).parameters


def shard_map(f, /, **kw):
    """``jax.shard_map`` under either spelling of the replication-check
    kwarg (``check_vma`` new, ``check_rep`` old); call sites use the new
    name."""
    if "check_vma" in kw and not _HAS_VMA:
        kw["check_rep"] = kw.pop("check_vma")
    return _shard_map(f, **kw)
