"""Cluster postmortem: merge per-node black-box dumps into one causal
timeline and name what went wrong.

Input: a ``PS_BLACKBOX_DIR`` full of ``blackbox-<proc>-<pid>.json``
dumps (utils/flightrec.py — one per process, written by the periodic
flusher, the stall watchdog, crash hooks or at exit). Output, via
``cli postmortem <dir>``:

- a **merged timeline**: every process's ring events on one wall-clock
  axis, each stamped with its process name/pid/tid;
- **cross-process stitching**: RPC events carry (cid, seq), so one
  logical push shows up as client ``rpc.issue`` -> server ``rpc.in`` ->
  server ``apply.commit`` -> client ``rpc.reply`` — the postmortem's
  analog of the tracing plane's trace-id propagation, but reconstructed
  from the wreckage instead of recorded live;
- **anomaly flags**: acked-but-unapplied pushes (a client holds an ok
  push reply no surviving server ledgered), RCU version regressions
  within one server life, reconnects that never healed, shed storms,
  and any watchdog stall dumps (source + thread named). The protocol
  detectors are not local code: they ARE the shared streaming monitors
  (analysis/monitors.py) the live audit plane (utils/auditor.py) runs
  at the coordinator, fed the merged timeline with end-of-stream
  semantics — one automaton per invariant, so the live and postmortem
  planes cannot drift (ISSUE 14). What stays postmortem-specific is
  the EVIDENCE gating: a live stream is complete by construction, a
  pile of wreckage is not, so acked-but-unapplied verdicts here are
  additionally gated on a surviving server box that saw the cid within
  its retained ring window;
- a **Perfetto-loadable** rendering through the existing trace exporter
  (``trace.write_chrome_trace``): load the merged timeline next to a
  PR-2 trace of the same run;
- the merged **per-key heat** view (telemetry ``key_heat`` snapshots
  ride every dump) — which keys were hot when the music stopped.
"""

from __future__ import annotations

import json
import os
from typing import Any

from parameter_server_tpu.utils.metrics import heat_top, merge_heat_snapshots

#: dump filename prefix (see flightrec.dump)
_PREFIX = "blackbox-"

#: the declared PASS-THROUGH inventory: flight-recorder events this
#: plane knows about but interprets only as timeline context — they are
#: stitched by (cid, seq) when they carry one (rpc.issue/rpc.out) and
#: rendered on the merged timeline, but no anomaly detector keys off
#: them. The pslint ``flightrec-contract`` checker diffs this set plus
#: the detectors' literal etype comparisons against every
#: ``flightrec.record()`` call site package-wide, BOTH ways: an emitted
#: event missing here (and from every detector) is wreckage nobody will
#: interpret; a name listed here that nobody emits is rename drift.
#: Growing this set is a deliberate, reviewed act.
_CONTEXT_EVENTS = frozenset({
    "apply.begin",       # multislice: batch entered the apply engine
    "coord.dead_worker", # coordinator sweep promoted a dead worker
    "freshness.serve",   # client serve booked a realized data age
    "heartbeat.beat",    # reporter liveness tick
    "mesh.apply",        # mesh backend: sharded update dispatched
    "mesh.pull",         # mesh backend: gather+psum pull issued
    "mesh.push",         # mesh backend: push payload (bytes post-quant)
    "prof.dump",         # continuous profiler wrote its exports
    "range.roll",        # beat guard rolled the per-range matrix
    "rpc.conn_died",     # wire: connection death observed
    "rpc.issue",         # client issue side of the (cid, seq) stitch
    "rpc.out",           # frame left the process
    "signal",            # fatal-signal crash hook fired
    "step.dispatch",     # trainer step anatomy
    "step.retire",
    "thread.exception",  # threading.excepthook crash hook fired
    "trace.promote",     # tail capture promoted a head-dropped trace
    "ts.roll",           # local time-series ring rolled a delta
    "watchdog.stall",    # stall firing (the dump's stalls list is the
                         # detector's source; the event is context)
})

#: the detectors'/stitchers' etype literals complementing
#: _CONTEXT_EVENTS for the RUNTIME unknown-event check below. Since
#: ISSUE 14 the protocol detectors are the shared streaming monitors,
#: so their consumed-event sets are UNIONED in from the registry —
#: the ssp.*/heal.*/rcu/apply/reply events moved out of the literal
#: list the day the monitors took them over (the pslint
#: ``flightrec-contract`` checker reads the registry's EVENTS sets the
#: same way, so the derivation and this set stay in lockstep).
from parameter_server_tpu.analysis.monitors import monitor_events as _mev

_DETECTOR_EVENTS = frozenset({
    "rpc.in",           # evidence windows (which server boxes saw a cid)
    "slo.alert",        # ISSUE 13: burn-rate engine firings
    "audit.violation",  # ISSUE 14: the live auditor's own verdicts
}) | _mev()


def unknown_events(timeline: list[dict[str, Any]]) -> dict[str, int]:
    """etype -> count for merged-timeline events NEITHER a detector nor
    the pass-through inventory knows. Nonempty means the dumps came from
    a build newer than this postmortem code (or flightrec-contract was
    bypassed) — the events still render on the timeline, but nothing
    interprets them."""
    seen: dict[str, int] = {}
    for ev in timeline:
        et = ev["etype"]
        if et not in _CONTEXT_EVENTS and et not in _DETECTOR_EVENTS:
            seen[et] = seen.get(et, 0) + 1
    return seen


def load_dumps(box_dir: str) -> list[dict[str, Any]]:
    """Every parseable ``blackbox-*.json`` in the dir (skipping torn or
    foreign files — a postmortem must work with whatever survived)."""
    out: list[dict[str, Any]] = []
    for fn in sorted(os.listdir(box_dir)):
        if not (fn.startswith(_PREFIX) and fn.endswith(".json")):
            continue
        try:
            with open(os.path.join(box_dir, fn)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if doc.get("schema") != "psbb/1":
            continue
        doc["_file"] = fn
        out.append(doc)
    return out


def crash_sidecars(box_dir: str) -> list[str]:
    """faulthandler ``.crash.txt`` sidecars present in the dir (a fatal
    signal dumped C-level stacks there; surfaced, not parsed)."""
    return sorted(
        fn
        for fn in os.listdir(box_dir)
        if fn.startswith(_PREFIX) and fn.endswith(".crash.txt")
        and os.path.getsize(os.path.join(box_dir, fn)) > 0
    )


def merge_timeline(dumps: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """All dumps' ring events on one wall-clock axis (ts ascending),
    each normalized to {ts, proc, pid, tid, etype, args}."""
    out: list[dict[str, Any]] = []
    for d in dumps:
        proc, pid = d.get("process", "?"), d.get("pid", 0)
        for ev in d.get("events", []):
            try:
                ts, tid, etype, args = ev
            except (TypeError, ValueError):
                continue
            out.append({
                "ts": float(ts), "proc": proc, "pid": pid, "tid": tid,
                "etype": etype, "args": args or {},
            })
    out.sort(key=lambda e: e["ts"])
    return out


def _call_key(ev: dict[str, Any]) -> tuple[str, str] | None:
    a = ev["args"]
    cid, seq = a.get("cid"), a.get("seq")
    if cid is None or seq is None:
        return None
    return (str(cid), str(seq))


def stitch_calls(
    timeline: list[dict[str, Any]],
) -> dict[tuple[str, str], list[dict[str, Any]]]:
    """Group events by (cid, seq) — the wire's dedup identity doubles as
    the postmortem's stitch key. ``apply.commit`` events contribute every
    (cid, seq) pair in their batch."""
    out: dict[tuple[str, str], list[dict[str, Any]]] = {}
    for ev in timeline:
        k = _call_key(ev)
        if k is not None:
            out.setdefault(k, []).append(ev)
        for pair in ev["args"].get("pairs", ()):
            try:
                cid, seq = pair
            except (TypeError, ValueError):
                continue
            if cid is None:
                continue
            out.setdefault((str(cid), str(seq)), []).append(ev)
    return out


def find_anomalies(
    dumps: list[dict[str, Any]],
    timeline: list[dict[str, Any]],
    shed_storm_n: int = 10,
    shed_window_s: float = 1.0,
) -> list[dict[str, Any]]:
    """The flag list (each: {kind, detail fields...}), most severe first."""
    out: list[dict[str, Any]] = []

    # watchdog stalls: the dump itself names the sources and threads
    # (the full firing history when present; older/synthetic dumps fall
    # back to the trigger reasons + the last firing's extra)
    for d in dumps:
        stalls = d.get("stalls")
        if stalls is None:
            stalls = []
            for r in d.get("trigger_reasons", []):
                if not r.startswith("stall:"):
                    continue
                src = r[len("stall:"):]
                st = d.get("stall") or {}
                if st.get("source") not in (None, src):
                    st = {}  # the extra belongs to a different firing
                stalls.append({
                    "source": st.get("source", src),
                    "thread": st.get("thread", ""),
                    "stalled_s": st.get("stalled_s"),
                })
        for st in stalls:
            out.append({
                "kind": "stall",
                "proc": d.get("process"),
                "source": st.get("source"),
                "thread": st.get("thread", ""),
                "stalled_s": st.get("stalled_s"),
            })
        for r in d.get("trigger_reasons", []):
            if r.startswith("thread-exception"):
                out.append({
                    "kind": "thread-exception",
                    "proc": d.get("process"),
                    "detail": r,
                })

    calls = stitch_calls(timeline)

    # The protocol detectors: the SHARED streaming monitors
    # (analysis/monitors.py), fed the merged timeline offline — life is
    # (proc, pid), the watermark clock is event time, and finish()
    # judges everything still unpaired at end-of-stream. The live
    # auditor (utils/auditor.py) runs the same automata at the
    # coordinator, so the two planes flag the same anomaly set from the
    # same event stream by construction.
    from parameter_server_tpu.analysis import monitors as monitors_mod

    mons = monitors_mod.make_monitors(
        shed_storm_n=shed_storm_n, shed_storm_window_s=shed_window_s,
    )
    viols: list[dict[str, Any]] = []
    for ev in timeline:
        nev = {
            "ts": ev["ts"], "life": (ev["proc"], ev["pid"]),
            "etype": ev["etype"], "args": ev["args"], "at": ev["ts"],
        }
        for m in mons:
            if ev["etype"] in m.EVENTS:
                viols += m.feed(nev)
    for m in mons:
        viols += m.finish()

    # Postmortem-specific EVIDENCE gating for acked-but-unapplied: only
    # judged when a server dump that saw THIS cid exists (otherwise the
    # server's box simply didn't survive, which is absence of
    # evidence), and only for acks inside that server ring's retained
    # window. The ring is bounded: a server records more events per
    # push than the client, so on a long healthy run the oldest client
    # replies outlive their commits' ring slots — those are evictions,
    # not anomalies. A commit always precedes the ack it triggers, so
    # an ack at ts >= the server window start would have its commit
    # retained.
    win_start: dict[tuple[str, int], float] = {}
    for ev in timeline:  # ts-sorted: first hit is each box's oldest event
        win_start.setdefault((ev["proc"], ev["pid"]), ev["ts"])
    server_cid_win: dict[str, float] = {}
    for ev in timeline:
        if ev["etype"] in ("rpc.in", "apply.commit", "apply.replay"):
            cids = []
            cid = ev["args"].get("cid")
            if cid is not None:
                cids.append(str(cid))
            for pair in ev["args"].get("pairs", ()):
                if pair and pair[0] is not None:
                    cids.append(str(pair[0]))
            w = win_start[(ev["proc"], ev["pid"])]
            for c in cids:
                server_cid_win[c] = min(server_cid_win.get(c, w), w)

    for v in viols:
        kind = v["kind"]
        if kind == "acked-but-unapplied":
            cid, seq = v["cid"], v["seq"]
            win = server_cid_win.get(cid)
            if win is None or v.get("ack_ts", 0.0) < win:
                continue  # no surviving server evidence: no verdict
            out.append({
                "kind": kind, "cid": cid, "seq": seq,
                "procs": sorted(
                    {e["proc"] for e in calls.get((cid, seq), ())}
                ),
            })
        elif kind in ("version-regression", "reconnect-without-heal"):
            proc, pid = v["life"]
            flat = {
                k: x for k, x in v.items()
                if k not in ("life", "monitor")
            }
            out.append({**flat, "proc": proc, "pid": pid})
        else:  # shed-storm, double-applied, ssp-staleness, future kinds
            flat = {
                k: x for k, x in v.items() if k not in ("life", "monitor")
            }
            out.append(flat)

    # SLO alerts (ISSUE 13): the coordinator's burn-rate engine fired —
    # each rising edge is one episode, rendered with its burn multiples
    # so the postmortem reads "which objective was burning, how hard"
    for ev in timeline:
        if ev["etype"] == "slo.alert":
            a = ev["args"]
            out.append({
                "kind": "slo-alert",
                "proc": ev["proc"],
                "rule": a.get("rule"),
                "node": a.get("node"),
                "burn_short": a.get("burn_short"),
                "burn_long": a.get("burn_long"),
                "ts": ev["ts"],
            })

    # audit.violation (ISSUE 14): the LIVE auditor's verdicts land in
    # the coordinator's black box — a postmortem over a cluster that
    # ran with the audit plane armed replays what the sentinel saw
    for ev in timeline:
        if ev["etype"] == "audit.violation":
            a = ev["args"]
            out.append({
                "kind": "audit-violation",
                "proc": ev["proc"],
                "violation": a.get("kind"),
                "node": a.get("node"),
                "ts": ev["ts"],
                **{
                    k: a[k] for k in ("cid", "seq", "worker") if k in a
                },
            })
    return out


def merged_heat(dumps: list[dict[str, Any]]) -> dict[str, Any]:
    """The cluster's per-key heat at dump time (telemetry piggyback)."""
    return merge_heat_snapshots([
        (d.get("telemetry") or {}).get("key_heat") or {} for d in dumps
    ])


def to_trace_events(timeline: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """The merged timeline as Chrome instant events (one Perfetto track
    per process/thread, same schema the tracing plane exports)."""
    return [
        {
            "name": ev["etype"],
            "cat": "blackbox",
            "ph": "i",
            "s": "t",
            "ts": ev["ts"] * 1e6,
            "pid": ev["pid"],
            "tid": ev["tid"],
            "args": dict(ev["args"]),
        }
        for ev in timeline
    ]


def export_trace(
    dumps: list[dict[str, Any]],
    timeline: list[dict[str, Any]],
    path: str,
) -> str:
    """Write the Perfetto-loadable rendering via the existing trace
    exporter (thread names recovered from each dump's stack section)."""
    from parameter_server_tpu.utils import trace

    tnames: dict[tuple[int, int], str] = {}
    for d in dumps:
        for t in d.get("threads", []):
            # events record thread IDENTS (the cheap id — see
            # flightrec._live_record); the dump's thread table maps them
            # back to names
            ident = t.get("ident")
            if ident is not None:
                tnames[(d.get("pid", 0), ident)] = t.get("name", "")
    return trace.write_chrome_trace(
        to_trace_events(timeline), path,
        process_names={
            d.get("pid", 0): d.get("process", "?") for d in dumps
        },
        thread_names=tnames,
    )


def render_report(
    dumps: list[dict[str, Any]],
    timeline: list[dict[str, Any]],
    anomalies: list[dict[str, Any]],
    tail: int = 40,
) -> str:
    """The human postmortem: per-process box inventory, anomaly flags,
    hot keys, and the merged timeline's tail."""
    lines = [f"postmortem over {len(dumps)} process box(es)"]
    lines.append("")
    lines.append(
        f"{'process':<18} {'pid':>7} {'events':>7} {'reason':<24} window"
    )
    for d in dumps:
        evs = d.get("events", [])
        window = (
            f"{evs[0][0]:.3f} .. {evs[-1][0]:.3f}" if evs else "-"
        )
        lines.append(
            f"{d.get('process', '?'):<18} {d.get('pid', 0):>7} "
            f"{len(evs):>7} {str(d.get('reason', '?')):<24} {window}"
        )
    lines.append("")
    if anomalies:
        lines.append(f"ANOMALIES ({len(anomalies)}):")
        for a in anomalies:
            kind = a["kind"]
            rest = ", ".join(
                f"{k}={v}" for k, v in a.items() if k != "kind"
            )
            lines.append(f"  [{kind}] {rest}")
    else:
        lines.append("no anomalies flagged")
    unknown = unknown_events(timeline)
    if unknown:
        lines.append("")
        lines.append(
            f"UNINTERPRETED event type(s) ({len(unknown)}) — dumps from "
            "a newer build than this postmortem code?"
        )
        for et, n in sorted(unknown.items()):
            lines.append(f"  {et} x{n}")
    heat = merged_heat(dumps)
    if heat:
        lines.append("")
        lines.append(
            f"hot keys at dump time ({heat.get('n', 0)} accesses, top 10):"
        )
        for key, c in heat_top(heat, 10):
            lines.append(f"  key {key:<24} ~{c}")
    if timeline:
        lines.append("")
        lines.append(f"merged timeline (last {min(tail, len(timeline))} "
                     f"of {len(timeline)} events):")
        for ev in timeline[-tail:]:
            args = " ".join(
                f"{k}={v}" for k, v in sorted(ev["args"].items())
                if k != "pairs"
            )
            lines.append(
                f"  {ev['ts']:.6f} {ev['proc']:<14} tid={ev['tid']:<8} "
                f"{ev['etype']:<20} {args}"
            )
    return "\n".join(lines)


def postmortem(
    box_dir: str, trace_out: str = "", tail: int = 40,
) -> dict[str, Any]:
    """End-to-end: load, merge, stitch, flag, render. Returns the
    machine-readable summary (the CLI prints the human report first)."""
    dumps = load_dumps(box_dir)
    timeline = merge_timeline(dumps)
    anomalies = find_anomalies(dumps, timeline)
    calls = stitch_calls(timeline)
    cross = sorted(
        k for k, evs in calls.items()
        if len({(e["proc"], e["pid"]) for e in evs}) >= 2
    )
    out: dict[str, Any] = {
        "processes": len(dumps),
        "events": len(timeline),
        "stitched_calls": len(calls),
        "cross_process_calls": len(cross),
        "anomalies": anomalies,
        "unknown_events": unknown_events(timeline),
        "crash_sidecars": crash_sidecars(box_dir) if dumps else [],
        "report": render_report(dumps, timeline, anomalies, tail=tail),
    }
    heat = merged_heat(dumps)
    if heat:
        out["heat_top"] = heat_top(heat, 10)
    if trace_out:
        out["trace_out"] = export_trace(dumps, timeline, trace_out)
    return out
