"""Cluster postmortem: merge per-node black-box dumps into one causal
timeline and name what went wrong.

Input: a ``PS_BLACKBOX_DIR`` full of ``blackbox-<proc>-<pid>.json``
dumps (utils/flightrec.py — one per process, written by the periodic
flusher, the stall watchdog, crash hooks or at exit). Output, via
``cli postmortem <dir>``:

- a **merged timeline**: every process's ring events on one wall-clock
  axis, each stamped with its process name/pid/tid;
- **cross-process stitching**: RPC events carry (cid, seq), so one
  logical push shows up as client ``rpc.issue`` -> server ``rpc.in`` ->
  server ``apply.commit`` -> client ``rpc.reply`` — the postmortem's
  analog of the tracing plane's trace-id propagation, but reconstructed
  from the wreckage instead of recorded live;
- **anomaly flags**: acked-but-unapplied pushes (a client holds an ok
  push reply no surviving server ledgered), RCU version regressions
  within one server life, reconnects that never healed, shed storms,
  and any watchdog stall dumps (source + thread named);
- a **Perfetto-loadable** rendering through the existing trace exporter
  (``trace.write_chrome_trace``): load the merged timeline next to a
  PR-2 trace of the same run;
- the merged **per-key heat** view (telemetry ``key_heat`` snapshots
  ride every dump) — which keys were hot when the music stopped.
"""

from __future__ import annotations

import json
import os
from typing import Any

from parameter_server_tpu.utils.metrics import heat_top, merge_heat_snapshots

#: dump filename prefix (see flightrec.dump)
_PREFIX = "blackbox-"

#: the declared PASS-THROUGH inventory: flight-recorder events this
#: plane knows about but interprets only as timeline context — they are
#: stitched by (cid, seq) when they carry one (rpc.issue/rpc.out) and
#: rendered on the merged timeline, but no anomaly detector keys off
#: them. The pslint ``flightrec-contract`` checker diffs this set plus
#: the detectors' literal etype comparisons against every
#: ``flightrec.record()`` call site package-wide, BOTH ways: an emitted
#: event missing here (and from every detector) is wreckage nobody will
#: interpret; a name listed here that nobody emits is rename drift.
#: Growing this set is a deliberate, reviewed act.
_CONTEXT_EVENTS = frozenset({
    "apply.begin",       # multislice: batch entered the apply engine
    "coord.dead_worker", # coordinator sweep promoted a dead worker
    "heartbeat.beat",    # reporter liveness tick
    "mesh.apply",        # mesh backend: sharded update dispatched
    "mesh.pull",         # mesh backend: gather+psum pull issued
    "mesh.push",         # mesh backend: push payload (bytes post-quant)
    "prof.dump",         # continuous profiler wrote its exports
    "rpc.conn_died",     # wire: connection death observed
    "rpc.issue",         # client issue side of the (cid, seq) stitch
    "rpc.out",           # frame left the process
    "signal",            # fatal-signal crash hook fired
    "ssp.finish",        # SSP clock movement
    "ssp.retire",        # SSP retirement (dead/reassigned worker)
    "ssp.wait",          # SSP gate blocked a worker (blocked ms)
    "step.dispatch",     # trainer step anatomy
    "step.retire",
    "thread.exception",  # threading.excepthook crash hook fired
    "ts.roll",           # local time-series ring rolled a delta
    "watchdog.stall",    # stall firing (the dump's stalls list is the
                         # detector's source; the event is context)
})

#: the detectors'/stitchers' etype literals, repeated as one set so the
#: RUNTIME unknown-event check below can complement _CONTEXT_EVENTS
#: (the flightrec-contract checker derives its "known" side from the
#: actual comparisons in this file, not from this convenience set)
_DETECTOR_EVENTS = frozenset({
    "rpc.in", "rpc.reply", "apply.commit", "apply.replay", "rcu.publish",
    "rpc.heal.begin", "rpc.healed", "rpc.heal.failed", "serve.shed",
    "slo.alert",
})


def unknown_events(timeline: list[dict[str, Any]]) -> dict[str, int]:
    """etype -> count for merged-timeline events NEITHER a detector nor
    the pass-through inventory knows. Nonempty means the dumps came from
    a build newer than this postmortem code (or flightrec-contract was
    bypassed) — the events still render on the timeline, but nothing
    interprets them."""
    seen: dict[str, int] = {}
    for ev in timeline:
        et = ev["etype"]
        if et not in _CONTEXT_EVENTS and et not in _DETECTOR_EVENTS:
            seen[et] = seen.get(et, 0) + 1
    return seen


def load_dumps(box_dir: str) -> list[dict[str, Any]]:
    """Every parseable ``blackbox-*.json`` in the dir (skipping torn or
    foreign files — a postmortem must work with whatever survived)."""
    out: list[dict[str, Any]] = []
    for fn in sorted(os.listdir(box_dir)):
        if not (fn.startswith(_PREFIX) and fn.endswith(".json")):
            continue
        try:
            with open(os.path.join(box_dir, fn)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if doc.get("schema") != "psbb/1":
            continue
        doc["_file"] = fn
        out.append(doc)
    return out


def crash_sidecars(box_dir: str) -> list[str]:
    """faulthandler ``.crash.txt`` sidecars present in the dir (a fatal
    signal dumped C-level stacks there; surfaced, not parsed)."""
    return sorted(
        fn
        for fn in os.listdir(box_dir)
        if fn.startswith(_PREFIX) and fn.endswith(".crash.txt")
        and os.path.getsize(os.path.join(box_dir, fn)) > 0
    )


def merge_timeline(dumps: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """All dumps' ring events on one wall-clock axis (ts ascending),
    each normalized to {ts, proc, pid, tid, etype, args}."""
    out: list[dict[str, Any]] = []
    for d in dumps:
        proc, pid = d.get("process", "?"), d.get("pid", 0)
        for ev in d.get("events", []):
            try:
                ts, tid, etype, args = ev
            except (TypeError, ValueError):
                continue
            out.append({
                "ts": float(ts), "proc": proc, "pid": pid, "tid": tid,
                "etype": etype, "args": args or {},
            })
    out.sort(key=lambda e: e["ts"])
    return out


def _call_key(ev: dict[str, Any]) -> tuple[str, str] | None:
    a = ev["args"]
    cid, seq = a.get("cid"), a.get("seq")
    if cid is None or seq is None:
        return None
    return (str(cid), str(seq))


def stitch_calls(
    timeline: list[dict[str, Any]],
) -> dict[tuple[str, str], list[dict[str, Any]]]:
    """Group events by (cid, seq) — the wire's dedup identity doubles as
    the postmortem's stitch key. ``apply.commit`` events contribute every
    (cid, seq) pair in their batch."""
    out: dict[tuple[str, str], list[dict[str, Any]]] = {}
    for ev in timeline:
        k = _call_key(ev)
        if k is not None:
            out.setdefault(k, []).append(ev)
        for pair in ev["args"].get("pairs", ()):
            try:
                cid, seq = pair
            except (TypeError, ValueError):
                continue
            if cid is None:
                continue
            out.setdefault((str(cid), str(seq)), []).append(ev)
    return out


def _applied_keys(
    calls: dict[tuple[str, str], list[dict[str, Any]]],
) -> set[tuple[str, str]]:
    return {
        k
        for k, evs in calls.items()
        if any(e["etype"] in ("apply.commit", "apply.replay") for e in evs)
    }


def find_anomalies(
    dumps: list[dict[str, Any]],
    timeline: list[dict[str, Any]],
    shed_storm_n: int = 10,
    shed_window_s: float = 1.0,
) -> list[dict[str, Any]]:
    """The flag list (each: {kind, detail fields...}), most severe first."""
    out: list[dict[str, Any]] = []

    # watchdog stalls: the dump itself names the sources and threads
    # (the full firing history when present; older/synthetic dumps fall
    # back to the trigger reasons + the last firing's extra)
    for d in dumps:
        stalls = d.get("stalls")
        if stalls is None:
            stalls = []
            for r in d.get("trigger_reasons", []):
                if not r.startswith("stall:"):
                    continue
                src = r[len("stall:"):]
                st = d.get("stall") or {}
                if st.get("source") not in (None, src):
                    st = {}  # the extra belongs to a different firing
                stalls.append({
                    "source": st.get("source", src),
                    "thread": st.get("thread", ""),
                    "stalled_s": st.get("stalled_s"),
                })
        for st in stalls:
            out.append({
                "kind": "stall",
                "proc": d.get("process"),
                "source": st.get("source"),
                "thread": st.get("thread", ""),
                "stalled_s": st.get("stalled_s"),
            })
        for r in d.get("trigger_reasons", []):
            if r.startswith("thread-exception"):
                out.append({
                    "kind": "thread-exception",
                    "proc": d.get("process"),
                    "detail": r,
                })

    calls = stitch_calls(timeline)
    applied = _applied_keys(calls)

    # acked-but-unapplied pushes: a client-side ok push reply whose
    # (cid, seq) no server event ever ledgered — only judged when a
    # server dump that saw THIS cid exists (otherwise the server's box
    # simply didn't survive, which is absence of evidence), and only for
    # acks inside that server ring's retained window. The ring is
    # bounded: a server records more events per push than the client, so
    # on a long healthy run the oldest client replies outlive their
    # commits' ring slots — those are evictions, not anomalies. A commit
    # always precedes the ack it triggers, so an ack at ts >= the
    # server window start would have its commit retained.
    win_start: dict[tuple[str, int], float] = {}
    for ev in timeline:  # ts-sorted: first hit is each box's oldest event
        win_start.setdefault((ev["proc"], ev["pid"]), ev["ts"])
    server_cid_win: dict[str, float] = {}
    for ev in timeline:
        if ev["etype"] in ("rpc.in", "apply.commit", "apply.replay"):
            cids = []
            cid = ev["args"].get("cid")
            if cid is not None:
                cids.append(str(cid))
            for pair in ev["args"].get("pairs", ()):
                if pair and pair[0] is not None:
                    cids.append(str(pair[0]))
            w = win_start[(ev["proc"], ev["pid"])]
            for c in cids:
                server_cid_win[c] = min(server_cid_win.get(c, w), w)
    for k, evs in sorted(calls.items()):
        if k in applied or k[0] not in server_cid_win:
            continue
        ack_ts = max(
            (
                e["ts"]
                for e in evs
                if e["etype"] == "rpc.reply"
                and e["args"].get("cmd") == "push"
                and e["args"].get("ok", True)
            ),
            default=None,
        )
        if ack_ts is None or ack_ts < server_cid_win[k[0]]:
            continue
        out.append({
            "kind": "acked-but-unapplied",
            "cid": k[0], "seq": k[1],
            "procs": sorted({e["proc"] for e in evs}),
        })

    # RCU version regressions within one process life (pid): versions
    # are opaque but monotonic per life — a decrease means a rollback
    # or a torn publish
    last_ver: dict[tuple[str, int], int] = {}
    for ev in timeline:
        if ev["etype"] != "rcu.publish":
            continue
        ver = ev["args"].get("ver")
        if ver is None:
            continue
        pk = (ev["proc"], ev["pid"])
        prev = last_ver.get(pk)
        if prev is not None and int(ver) < prev:
            out.append({
                "kind": "version-regression",
                "proc": ev["proc"], "pid": ev["pid"],
                "from": prev, "to": int(ver), "ts": ev["ts"],
            })
        last_ver[pk] = int(ver)

    # reconnects without heals: a process whose heal attempts never
    # landed — its peer died (or the net partitioned) and stayed gone
    by_proc: dict[tuple[str, int], dict[str, int]] = {}
    for ev in timeline:
        if ev["etype"] in ("rpc.heal.begin", "rpc.healed", "rpc.heal.failed"):
            c = by_proc.setdefault((ev["proc"], ev["pid"]), {})
            c[ev["etype"]] = c.get(ev["etype"], 0) + 1
    for (proc, pid), c in sorted(by_proc.items()):
        begun = c.get("rpc.heal.begin", 0)
        healed = c.get("rpc.healed", 0)
        if begun > healed:
            out.append({
                "kind": "reconnect-without-heal",
                "proc": proc, "pid": pid,
                "begun": begun, "healed": healed,
                "failed": c.get("rpc.heal.failed", 0),
            })

    # SLO alerts (ISSUE 13): the coordinator's burn-rate engine fired —
    # each rising edge is one episode, rendered with its burn multiples
    # so the postmortem reads "which objective was burning, how hard"
    for ev in timeline:
        if ev["etype"] == "slo.alert":
            a = ev["args"]
            out.append({
                "kind": "slo-alert",
                "proc": ev["proc"],
                "rule": a.get("rule"),
                "node": a.get("node"),
                "burn_short": a.get("burn_short"),
                "burn_long": a.get("burn_long"),
                "ts": ev["ts"],
            })

    # shed storms: admission control firing in bursts — readers were
    # being bounced faster than the engine drained
    sheds = [e["ts"] for e in timeline if e["etype"] == "serve.shed"]
    lo = 0
    for hi in range(len(sheds)):
        while sheds[hi] - sheds[lo] > shed_window_s:
            lo += 1
        if hi - lo + 1 >= shed_storm_n:
            out.append({
                "kind": "shed-storm",
                "count": hi - lo + 1,
                "window_s": shed_window_s,
                "ts": sheds[lo],
            })
            break
    return out


def merged_heat(dumps: list[dict[str, Any]]) -> dict[str, Any]:
    """The cluster's per-key heat at dump time (telemetry piggyback)."""
    return merge_heat_snapshots([
        (d.get("telemetry") or {}).get("key_heat") or {} for d in dumps
    ])


def to_trace_events(timeline: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """The merged timeline as Chrome instant events (one Perfetto track
    per process/thread, same schema the tracing plane exports)."""
    return [
        {
            "name": ev["etype"],
            "cat": "blackbox",
            "ph": "i",
            "s": "t",
            "ts": ev["ts"] * 1e6,
            "pid": ev["pid"],
            "tid": ev["tid"],
            "args": dict(ev["args"]),
        }
        for ev in timeline
    ]


def export_trace(
    dumps: list[dict[str, Any]],
    timeline: list[dict[str, Any]],
    path: str,
) -> str:
    """Write the Perfetto-loadable rendering via the existing trace
    exporter (thread names recovered from each dump's stack section)."""
    from parameter_server_tpu.utils import trace

    tnames: dict[tuple[int, int], str] = {}
    for d in dumps:
        for t in d.get("threads", []):
            # events record thread IDENTS (the cheap id — see
            # flightrec._live_record); the dump's thread table maps them
            # back to names
            ident = t.get("ident")
            if ident is not None:
                tnames[(d.get("pid", 0), ident)] = t.get("name", "")
    return trace.write_chrome_trace(
        to_trace_events(timeline), path,
        process_names={
            d.get("pid", 0): d.get("process", "?") for d in dumps
        },
        thread_names=tnames,
    )


def render_report(
    dumps: list[dict[str, Any]],
    timeline: list[dict[str, Any]],
    anomalies: list[dict[str, Any]],
    tail: int = 40,
) -> str:
    """The human postmortem: per-process box inventory, anomaly flags,
    hot keys, and the merged timeline's tail."""
    lines = [f"postmortem over {len(dumps)} process box(es)"]
    lines.append("")
    lines.append(
        f"{'process':<18} {'pid':>7} {'events':>7} {'reason':<24} window"
    )
    for d in dumps:
        evs = d.get("events", [])
        window = (
            f"{evs[0][0]:.3f} .. {evs[-1][0]:.3f}" if evs else "-"
        )
        lines.append(
            f"{d.get('process', '?'):<18} {d.get('pid', 0):>7} "
            f"{len(evs):>7} {str(d.get('reason', '?')):<24} {window}"
        )
    lines.append("")
    if anomalies:
        lines.append(f"ANOMALIES ({len(anomalies)}):")
        for a in anomalies:
            kind = a["kind"]
            rest = ", ".join(
                f"{k}={v}" for k, v in a.items() if k != "kind"
            )
            lines.append(f"  [{kind}] {rest}")
    else:
        lines.append("no anomalies flagged")
    unknown = unknown_events(timeline)
    if unknown:
        lines.append("")
        lines.append(
            f"UNINTERPRETED event type(s) ({len(unknown)}) — dumps from "
            "a newer build than this postmortem code?"
        )
        for et, n in sorted(unknown.items()):
            lines.append(f"  {et} x{n}")
    heat = merged_heat(dumps)
    if heat:
        lines.append("")
        lines.append(
            f"hot keys at dump time ({heat.get('n', 0)} accesses, top 10):"
        )
        for key, c in heat_top(heat, 10):
            lines.append(f"  key {key:<24} ~{c}")
    if timeline:
        lines.append("")
        lines.append(f"merged timeline (last {min(tail, len(timeline))} "
                     f"of {len(timeline)} events):")
        for ev in timeline[-tail:]:
            args = " ".join(
                f"{k}={v}" for k, v in sorted(ev["args"].items())
                if k != "pairs"
            )
            lines.append(
                f"  {ev['ts']:.6f} {ev['proc']:<14} tid={ev['tid']:<8} "
                f"{ev['etype']:<20} {args}"
            )
    return "\n".join(lines)


def postmortem(
    box_dir: str, trace_out: str = "", tail: int = 40,
) -> dict[str, Any]:
    """End-to-end: load, merge, stitch, flag, render. Returns the
    machine-readable summary (the CLI prints the human report first)."""
    dumps = load_dumps(box_dir)
    timeline = merge_timeline(dumps)
    anomalies = find_anomalies(dumps, timeline)
    calls = stitch_calls(timeline)
    cross = sorted(
        k for k, evs in calls.items()
        if len({(e["proc"], e["pid"]) for e in evs}) >= 2
    )
    out: dict[str, Any] = {
        "processes": len(dumps),
        "events": len(timeline),
        "stitched_calls": len(calls),
        "cross_process_calls": len(cross),
        "anomalies": anomalies,
        "unknown_events": unknown_events(timeline),
        "crash_sidecars": crash_sidecars(box_dir) if dumps else [],
        "report": render_report(dumps, timeline, anomalies, tail=tail),
    }
    heat = merged_heat(dumps)
    if heat:
        out["heat_top"] = heat_top(heat, 10)
    if trace_out:
        out["trace_out"] = export_trace(dumps, timeline, trace_out)
    return out
