"""Black-box flight recorder + stall watchdog + crash dumps.

Reference analog: none — the reference debugged dead parameter servers
with glog files and gdb on the corpse. This module is the aviation
answer instead: every process keeps an **always-on, lock-light, bounded
ring** of the last few thousand structured events (RPC frames in/out
with cid/seq/cmd, apply-batch begin/commit with the RCU version, RCU
publishes, reconnect/heal transitions, SSP clock movements, shed
decisions, heartbeats), and when something goes wrong — a stalled apply
thread, a wedged SSP clock, an unhandled thread exception, a fatal
signal, a chaos-soak assertion — the whole box (ring + telemetry
snapshot + every thread's stack) lands as one atomic JSON dump in
``PS_BLACKBOX_DIR``. ``cli postmortem <dir>`` (utils/postmortem.py)
merges the per-node dumps into one causal timeline.

Design constraints, in order (the PR-2 tracer's contract, restated):

1. **Disabled is free.** The module-level ``record`` is an
   identity-pinned no-op function while the recorder is disarmed —
   no event tuple, no buffer append, nothing for the GC (tests assert
   ``record is _noop_record``). Instrumentation therefore lives
   permanently on the wire/apply/clock hot paths.
2. **Armed is lock-light.** The ring is a ``deque(maxlen=capacity)``;
   ``append`` is GIL-atomic, so recording takes NO lock — a recorder
   must never become the contention it exists to diagnose.
3. **Survives the crash.** A background flusher re-dumps the box every
   ``flush_interval_s`` while armed, so even a SIGKILL'd process leaves
   an at-most-one-interval-stale box behind — the property the chaos
   soak's kill drills rely on. Trigger dumps (watchdog, excepthook,
   SIGTERM, atexit) are immediate; ``faulthandler`` covers the truly
   fatal signals with a ``.crash.txt`` sidecar.

Event schema (the ``psl``-style wire of the dump): each ring entry is
``[wall_ts_seconds, thread_ident, etype, fields]`` with ``fields`` a
small JSON-safe dict (the dump's thread table maps idents to
names/native ids). Call sites keep fields scalar (cid/seq/cmd/ver/rank) so
a dump stays a few hundred KB. The dump document::

    {"schema": "psbb/1", "process": name, "pid": ..., "reason": ...,
     "trigger_reasons": [...], "wall_time": ..., "events": [...],
     "telemetry": telemetry_snapshot(), "threads": [{name, ident,
     native_id, daemon, stack}], "stall": {...} | null}

Arming (the PS_FAULT_PLAN / PS_TRACE_DIR inheritance pattern): the
``PS_BLACKBOX_DIR`` env var arms the import-time recorder so spawned
multihost children inherit it for free; ``configure()`` re-arms
explicitly (``[blackbox]`` config section / launch_local's
``blackbox_dir=``).

The **stall watchdog** rides along: layers register ``(busy, progress)``
probes (``watchdog.register``) — the apply engine, the SSP clock, a
handle's pipelined reader, the heartbeat thread — and one daemon thread
per armed process samples them: a source that stays busy without its
progress counter advancing for ``stall_timeout_s`` fires exactly once
per stall episode, recording the event, bumping ``watchdog_stalls`` and
dumping the box with the stalled source + thread named.

The **audit event spool** (ISSUE 14) is the live half of the same
stream: while armed (``configure_spool``), every recorded event whose
etype is audit-relevant (:data:`AUDIT_EVENTS` — the protocol-invariant
carriers: push acks, apply commits/replays, RCU publishes, SSP clock
movements, heal transitions, sheds) ALSO lands in a bounded
:class:`EventSpool`. The heartbeat reporter drains the spool into
sequence-numbered batches piggybacked on each beat and acks them after
a successful send, so the coordinator's streaming auditor
(utils/auditor.py) sees an at-least-once, seq-deduplicated event stream
with explicit saturation accounting (a full spool drops NEW events and
counts them — the auditor reads the ``dropped`` watermark and knows the
stream has holes instead of trusting a silently truncated one).
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Callable

BLACKBOX_DIR_ENV = "PS_BLACKBOX_DIR"

#: ring default: ~4k events x ~100 B ~= a few hundred KB per dump
DEFAULT_CAPACITY = 4096


# -- the recorder -----------------------------------------------------------

_dir: str | None = None
_buf: deque | None = None
_name: str = ""
_reasons: list[str] = []  # trigger reasons, in firing order
_stall_log: list[dict[str, Any]] = []  # every watchdog firing this life
_dump_lock = threading.Lock()  # one dump writer at a time
_flush_stop: threading.Event | None = None
_crash_file = None  # faulthandler sidecar handle (kept alive on purpose)


def _noop_record(etype: str, **fields: Any) -> None:
    """The disarmed path: identity-pinned (tests assert ``record is
    _noop_record``) and allocation-free beyond the caller's kwargs."""


def _live_record(etype: str, **fields: Any) -> None:
    # ONE event tuple serves both sinks (ring + audit spool).
    # get_ident, NOT get_native_id: the ident is a userspace read
    # (~0.1 us) where the native id is a gettid syscall that costs
    # ~100x on un-vDSO'd kernels — on a per-frame hot path that
    # difference IS the recorder's overhead budget. Dumps map ident
    # -> name/native_id through their thread table.
    ev = (time.time(), threading.get_ident(), etype, fields)
    buf = _buf
    if buf is not None:
        buf.append(ev)
    sp = _spool
    if sp is not None and etype in AUDIT_EVENTS:
        sp.offer(etype, ev)


#: the module-level recording entry point every instrumented layer calls
#: (``flightrec.record(...)``): rebound between the no-op and the live
#: path whenever the ring (configure) or the audit spool
#: (configure_spool) arms/disarms, so the disabled cost is one attribute
#: load + one call that does nothing
record = _noop_record


def _rebind_record() -> None:
    """record is live iff ANY sink (ring, audit spool) is armed; with
    both off it is the identity-pinned no-op the overhead tests assert."""
    global record
    record = _noop_record if (_buf is None and _spool is None) else _live_record


def enabled() -> bool:
    return _dir is not None


def blackbox_dir() -> str | None:
    return _dir


def events() -> list[tuple]:
    """Snapshot of the ring (newest last); empty when disarmed."""
    buf = _buf
    return list(buf) if buf is not None else []


# -- audit event spool (ISSUE 14) -------------------------------------------

#: the audit-relevant slice of the event stream: exactly the etypes the
#: streaming monitors (analysis/monitors.py) consume. Everything else
#: (rpc.in frame noise, trace/step context) stays ring-only — the spool
#: rides heartbeats and must stay beat-sized.
AUDIT_EVENTS = frozenset({
    "rpc.issue", "rpc.reply",           # client push issue/ack (push-only)
    "apply.commit", "apply.replay",     # server exactly-once ledger proof
    "rcu.publish",                      # snapshot version stream
    "ssp.wait", "ssp.finish", "ssp.retire",  # clock movements
    "rpc.conn_died",                    # heal-chain context
    "rpc.heal.begin", "rpc.healed", "rpc.heal.failed",
    "serve.shed",                       # admission-control firings
})

#: rpc.* issue/ack traffic is per-CALL volume; only push carries the
#: exactly-once invariant the auditor checks, so pulls/control calls
#: stay out of the spool entirely (they would saturate it for nothing)
_AUDIT_RPC_CMDS = frozenset({"push"})


class EventSpool:
    """Bounded spool of audit events, drained as sequence-numbered
    batches by the heartbeat thread.

    Producers (``record`` on any thread) ``offer`` events lock-free:
    a deque append is GIL-atomic, and the capacity check is a cheap
    ``len`` — the bound is therefore soft by at most the number of
    concurrently appending threads, which is fine for a memory guard.
    A full spool drops the NEW event and counts it (saturation
    accounting): the drop watermark rides every batch, so the consumer
    KNOWS the stream has holes — the difference between "no anomaly"
    and "no evidence".

    The drain side is single-consumer (the process's heartbeat thread,
    or the coordinator draining its own spool inline): ``drain()``
    returns the still-unacked in-flight batches plus newly cut ones,
    ``ack()`` discards the in-flight set once the carrying beat
    succeeded. A beat that dies on the wire simply leaves the batches
    in flight — the next beat re-ships them under the SAME seq numbers
    and the auditor's per-node seq dedup drops the duplicates."""

    def __init__(self, capacity: int = 4096, batch_events: int = 512):
        self.capacity = max(int(capacity), 16)
        self.batch_events = max(int(batch_events), 1)
        self._buf: deque = deque()
        self._next_seq = 0
        self._inflight: list[dict[str, Any]] = []
        self._lock = threading.Lock()  # drain/ack only — never offer

    def offer(self, etype: str, ev: tuple) -> None:
        """Hot-path admission (called from ``record``): lock-free."""
        if etype in ("rpc.issue", "rpc.reply"):
            if ev[3].get("cmd") not in _AUDIT_RPC_CMDS:
                return
        if len(self._buf) >= self.capacity:
            # saturation: drop NEW (the retained prefix stays causally
            # contiguous; a drop-oldest spool would silently shear the
            # pairing windows the monitors reason over)
            from parameter_server_tpu.utils.metrics import wire_counters

            wire_counters.inc("audit_spool_dropped")
            return
        self._buf.append(ev)

    def __len__(self) -> int:
        return len(self._buf)

    @property
    def dropped(self) -> int:
        """Cumulative saturation drops (the batch watermark)."""
        from parameter_server_tpu.utils.metrics import wire_counters

        return wire_counters.get("audit_spool_dropped")

    def drain(self, max_batches: int = 4) -> list[dict[str, Any]]:
        """Cut up to ``max_batches`` total batches (unacked in-flight
        ones first, re-shipped verbatim) for one beat's piggyback."""
        with self._lock:
            out = list(self._inflight)
            dropped = self.dropped
            while len(out) < max_batches:
                evs: list[list] = []
                while len(evs) < self.batch_events:
                    try:
                        ev = self._buf.popleft()
                    except IndexError:
                        break
                    evs.append([ev[0], ev[1], ev[2], ev[3]])
                if not evs:
                    break
                batch = {
                    "seq": self._next_seq,
                    "events": evs,
                    # cumulative drop watermark at cut time: the auditor
                    # diffs consecutive watermarks to find stream holes
                    "dropped": dropped,
                }
                self._next_seq += 1
                self._inflight.append(batch)
                out.append(batch)
            return out

    def ack(self) -> None:
        """The beat carrying the last ``drain()``'s batches landed."""
        with self._lock:
            self._inflight = []


_spool: EventSpool | None = None


def audit_spool() -> EventSpool | None:
    """The armed spool (None when the audit plane is off)."""
    return _spool


def configure_spool(
    capacity: int | None = 4096, batch_events: int = 512
) -> EventSpool | None:
    """Arm (capacity > 0) or disarm (``None``/``0``) the audit event
    spool, rebinding ``record`` so the disarmed-everything path stays
    the identity-pinned no-op. Re-arming swaps in a fresh spool."""
    global _spool
    _spool = (
        EventSpool(capacity, batch_events) if capacity else None
    )
    _rebind_record()
    return _spool


def dump(reason: str, extra: dict[str, Any] | None = None) -> str | None:
    """Atomically write this process's box (ring + telemetry + thread
    stacks) into the armed dir; returns the path (None when disarmed).
    One file per process — later dumps overwrite earlier ones, and
    ``trigger_reasons`` keeps the firing history. Never raises: a dump
    is last-ditch diagnostics and must not mask the original failure."""
    d, buf = _dir, _buf
    if d is None or buf is None:
        return None
    try:
        if reason != "periodic" and len(_reasons) < 32:
            # the flusher's cadence is not a trigger; real triggers keep
            # a bounded firing history across overwrites
            _reasons.append(reason)
        threads = []
        frames = sys._current_frames()
        for t in threading.enumerate():
            fr = frames.get(t.ident)
            threads.append({
                "name": t.name,
                "ident": t.ident,
                "native_id": getattr(t, "native_id", None),
                "daemon": t.daemon,
                "stack": traceback.format_stack(fr) if fr is not None else [],
            })
        from parameter_server_tpu.utils.metrics import (
            telemetry_snapshot,
            wire_counters,
        )

        wire_counters.inc("blackbox_dumps")
        doc = {
            "schema": "psbb/1",
            "process": _name,
            "pid": os.getpid(),
            "reason": reason,
            "trigger_reasons": list(_reasons),
            "wall_time": time.time(),
            "events": [list(e) for e in buf],
            # observe-only: rolling here would consume the peak windows
            # the heartbeat plane reports (the flusher dumps every second)
            "telemetry": telemetry_snapshot(roll_peaks=False),
            "threads": threads,
            "stall": extra,
            # the full watchdog firing history (dumps overwrite each
            # other, so the triggering stall alone would lose earlier
            # ones — e.g. the apply engine AND a handle reader both
            # wedging on one fault)
            "stalls": list(_stall_log),
        }
        path = os.path.join(d, f"blackbox-{_name}-{os.getpid()}.json")
        tmp = path + f".tmp.{threading.get_native_id()}"
        with _dump_lock:
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001 — diagnostics must never mask the crash
        return None


def _flush_loop(stop: threading.Event, interval_s: float) -> None:
    """Periodic persistence: the half of the black box that survives
    SIGKILL. Re-dumps only when the ring moved since the last flush."""
    last_tail: tuple | None = None
    while not stop.wait(interval_s):
        buf = _buf
        if buf is None:
            return
        tail = buf[-1] if buf else None
        if tail is not last_tail:
            last_tail = tail
            dump("periodic")


# -- crash hooks ------------------------------------------------------------

_prev_threading_hook = None
_hooks_installed = False


def _thread_excepthook(args) -> None:  # pragma: no cover - exercised via tests
    tname = args.thread.name if args.thread is not None else "?"
    record(
        "thread.exception", thread=tname,
        exc=repr(args.exc_value),
    )
    dump(f"thread-exception:{tname}")
    if _prev_threading_hook is not None:
        _prev_threading_hook(args)


def _sigterm_handler(signum, frame) -> None:  # pragma: no cover - signal path
    record("signal", sig=int(signum))
    # dump() takes the counter/telemetry/_dump locks; the handler runs on
    # whichever thread the signal interrupted, and if THAT frame holds one
    # of them an inline dump deadlocks and the process never dies. A
    # helper thread + bounded join always reaches the re-kill — worst
    # case the box is the flusher's, at most one interval stale.
    t = threading.Thread(
        target=dump, args=(f"signal:{signum}",), daemon=True,
        name="ps-blackbox-sig",
    )
    t.start()
    t.join(timeout=2.0)
    signal.signal(signum, signal.SIG_DFL)
    os.kill(os.getpid(), signum)


def _atexit_dump() -> None:  # pragma: no cover - interpreter teardown
    try:
        dump("exit")
    except Exception:  # noqa: BLE001
        pass


def _install_hooks() -> None:
    """Unhandled-thread-exception, SIGTERM and fatal-signal coverage.
    Installed once per process, first arm; the hooks themselves check
    the live armed state, so a later disarm makes them no-ops."""
    global _prev_threading_hook, _hooks_installed, _crash_file
    if _hooks_installed:
        return
    _hooks_installed = True
    _prev_threading_hook = threading.excepthook
    threading.excepthook = _thread_excepthook
    atexit.register(_atexit_dump)
    try:
        # SIGTERM: dump, then die with the default disposition. Only the
        # main thread may install handlers; non-main arming skips it.
        signal.signal(signal.SIGTERM, _sigterm_handler)
    except (ValueError, OSError):
        pass
    try:
        # truly fatal signals (SEGV/FPE/ABRT/BUS): python code cannot
        # run, but faulthandler's C dumper can — sidecar text file
        import faulthandler

        _crash_file = open(
            os.path.join(_dir, f"blackbox-{_name}-{os.getpid()}.crash.txt"),
            "w",
        )
        faulthandler.enable(file=_crash_file)
    except Exception:  # noqa: BLE001 — best-effort coverage
        pass


# -- stall watchdog ---------------------------------------------------------


class _Source:
    __slots__ = ("probe", "thread_name", "last", "mark", "fired")

    def __init__(self, probe: Callable, thread_name: str):
        self.probe = probe
        self.thread_name = thread_name
        self.last: Any = None
        self.mark = time.monotonic()
        self.fired = False


class Watchdog:
    """Per-process stall detector over registered progress probes.

    A probe is ``() -> (busy, progress)``: ``busy`` means the source
    currently has work it should be making progress on (a non-empty
    apply queue, workers parked on the SSP gate, requests in a client's
    pipeline window, a running heartbeat thread); ``progress`` is any
    value that changes whenever real progress happens. A source that
    stays busy with unchanged progress for ``stall_timeout_s`` fires
    ONCE per stall episode (re-armed the moment progress resumes):
    ``watchdog.stall`` event + ``watchdog_stalls`` counter + a blackbox
    dump whose ``stall`` section names the source and its thread.

    ``register``/``unregister`` are always cheap and safe to call —
    probes only run while an armed recorder's watchdog thread (or a
    test's explicit :meth:`poll`) drives them."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sources: dict[str, _Source] = {}
        self._thread: threading.Thread | None = None
        self._stop: threading.Event | None = None
        self.interval_s = 1.0
        self.stall_timeout_s = 30.0

    def register(
        self, name: str, probe: Callable, thread_name: str = ""
    ) -> None:
        with self._lock:
            self._sources[name] = _Source(probe, thread_name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def sources(self) -> list[str]:
        with self._lock:
            return sorted(self._sources)

    def poll(self, now: float | None = None) -> list[str]:
        """One sampling pass; returns the sources that fired (tests
        drive this directly for determinism)."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            items = list(self._sources.items())
        fired: list[str] = []
        for name, s in items:
            try:
                busy, prog = s.probe()
            except Exception:  # noqa: BLE001 — a dying probe is not a stall
                continue
            if not busy or prog != s.last:
                s.last = prog
                s.mark = now
                s.fired = False
                continue
            if not s.fired and now - s.mark >= self.stall_timeout_s:
                s.fired = True
                fired.append(name)
                self._fire(name, s, now - s.mark)
        return fired

    def _fire(self, name: str, s: _Source, stalled_s: float) -> None:
        from parameter_server_tpu.utils.metrics import wire_counters

        wire_counters.inc("watchdog_stalls")
        record(
            "watchdog.stall", source=name, thread=s.thread_name,
            stalled_s=round(stalled_s, 3),
        )
        extra = {
            "source": name,
            "thread": s.thread_name,
            "stalled_s": round(stalled_s, 3),
        }
        if len(_stall_log) < 32:
            _stall_log.append(extra)
        dump(f"stall:{name}", extra=extra)

    def start(self, interval_s: float, stall_timeout_s: float) -> None:
        self.interval_s = interval_s
        self.stall_timeout_s = stall_timeout_s
        if self._thread is not None:
            return
        self._stop = threading.Event()

        def loop(stop: threading.Event) -> None:
            while not stop.wait(self.interval_s):
                self.poll()

        self._thread = threading.Thread(
            target=loop, args=(self._stop,), daemon=True, name="ps-watchdog"
        )
        self._thread.start()

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        self._thread = None
        self._stop = None


#: process-global watchdog; layers register probes unconditionally (a
#: dict entry), the sampling thread only runs while the box is armed
watchdog = Watchdog()


# -- arming -----------------------------------------------------------------


def configure(
    blackbox_dir: str | None,
    capacity: int = DEFAULT_CAPACITY,
    process_name: str = "",
    flush_interval_s: float = 1.0,
    watchdog_interval_s: float = 1.0,
    stall_timeout_s: float = 30.0,
) -> None:
    """Arm (with a dir) or disarm (``""``/``None``) the recorder,
    rebinding the module-level ``record`` between the live and the
    identity-pinned no-op paths. Arming starts the periodic flusher and
    the watchdog thread and installs the crash hooks; re-arming swaps
    the ring (configure at process start, like the tracer)."""
    global _dir, _buf, _name, _reasons, _stall_log, _flush_stop
    # stop the previous incarnation's threads first (idempotent)
    if _flush_stop is not None:
        _flush_stop.set()
        _flush_stop = None
    watchdog.stop()
    if not blackbox_dir:
        _dir = None
        _buf = None
        _rebind_record()
        return
    os.makedirs(blackbox_dir, exist_ok=True)
    _dir = blackbox_dir
    _name = process_name or f"proc-{os.getpid()}"
    _reasons = []
    _stall_log = []
    _buf = deque(maxlen=max(int(capacity), 1))
    _rebind_record()
    _install_hooks()
    if flush_interval_s > 0:
        _flush_stop = threading.Event()
        threading.Thread(
            target=_flush_loop, args=(_flush_stop, flush_interval_s),
            daemon=True, name="ps-blackbox-flush",
        ).start()
    watchdog.start(watchdog_interval_s, stall_timeout_s)


# env-armed at import so spawned children need no plumbing (the
# PS_FAULT_PLAN pattern); run_node re-configures with a role-rank name
if os.environ.get(BLACKBOX_DIR_ENV):
    configure(os.environ[BLACKBOX_DIR_ENV])
