"""Distributed tracing: spans across every process in the pod.

Reference analog: the reference scheduler was a live dashboard fed by
Progress protos and heartbeat stats, but "where did this step's 40 ms go"
needed per-node timelines the reference never had. This module is that
timeline: a low-overhead :class:`Tracer` whose spans export as Chrome
trace-event JSON (load the file — or the merged file from
:func:`merge_trace_dir` — at https://ui.perfetto.dev), with
trace-id/parent-span propagation carried in the RPC header so one logical
``push`` renders as client-span -> server-dispatch-span -> updater-span
across processes.

Design constraints, in order:

1. **Disabled is free.** The default tracer is disabled; ``span()`` then
   returns one process-global no-op singleton — no Span object, no dict,
   no buffer append, nothing for the GC (tests assert the identity).
   Instrumentation can therefore live permanently on hot paths.
2. **Bounded.** Armed tracing records into a ring buffer
   (``deque(maxlen=capacity)``): a week-long run keeps the newest spans
   and never grows without bound.
3. **Cross-process by construction.** ``ts`` is wall-clock microseconds
   (the only clock two processes share), ``pid``/``tid`` are real OS ids,
   and every span carries ``trace_id``/``span_id``/``parent_id`` in its
   ``args`` so the RPC layer can stitch client and server timelines.

Arming (same inheritance pattern as ``PS_FAULT_PLAN``): the
``PS_TRACE_DIR`` env var arms the import-time global tracer — spawned
multihost children inherit it for free; ``configure()`` re-arms
explicitly (CLI ``--trace_dir`` / config ``[trace] trace_dir``). Each
armed process writes ``trace-<name>-<pid>.json`` into the directory at
exit (atexit backstop) or on ``tracer.flush()``.

API sketch::

    from parameter_server_tpu.utils import trace

    with trace.span("step.pull", cat="step", bytes=n):   # context manager
        ...
    @trace.traced("load_shard")                          # decorator
    def load_shard(...): ...
    trace.instant("rpc.retry", addr=addr)                # point event

    header["_trace"] = trace.wire_context()              # client side
    with trace.activate(header.pop("_trace", None)):     # server side
        ...spans here join the caller's trace...
"""

from __future__ import annotations

import atexit
import functools
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable

TRACE_DIR_ENV = "PS_TRACE_DIR"
TRACE_SAMPLE_ENV = "PS_TRACE_SAMPLE"

#: ring-buffer default: ~64k spans x ~200 B/event ~= 13 MB ceiling per process
DEFAULT_CAPACITY = 65536


def _env_sample() -> int:
    try:
        return max(1, int(os.environ.get(TRACE_SAMPLE_ENV, "1") or 1))
    except ValueError:
        return 1

_current = threading.local()  # .span: innermost live span (or remote parent)


def _now_us() -> float:
    """Wall-clock microseconds: the only timebase two processes share, so
    Perfetto lines up client and server spans on one axis."""
    return time.time() * 1e6


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


class _NoopSpan:
    """The disabled-path singleton: enter/exit/set are all no-ops and no
    instance is ever allocated per call — ``Tracer.span`` returns THIS
    object every time when tracing is off (the "tracing disabled is
    free" contract, asserted by tests)."""

    __slots__ = ()
    trace_id = None
    span_id = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set(self, **args: Any) -> None:
        pass


_NOOP = _NoopSpan()


class _DroppedSpan:
    """A span inside a head-DROPPED trace (``sample=1/N``): it keeps the
    thread-local nesting and a real wire identity — descendants, instants
    and remote callees all see the shared trace id and make the SAME drop
    decision, so sampling keeps whole traces or none of one — but records
    nothing into the buffer."""

    __slots__ = ("trace_id", "span_id", "_prev")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.span_id = _new_id()

    def set(self, **args: Any) -> None:
        pass

    def __enter__(self) -> "_DroppedSpan":
        self._prev = getattr(_current, "span", None)
        _current.span = self
        return self

    def __exit__(self, *exc: Any) -> bool:
        _current.span = self._prev
        return False


class Span:
    """One live span (context manager). Recorded as a Chrome ``"X"``
    (complete) event on exit; nesting via a thread-local stack gives
    parent ids without any caller plumbing."""

    __slots__ = (
        "_tracer", "name", "cat", "trace_id", "span_id", "parent_id",
        "args", "_t0_us", "_t0", "_prev",
    )

    def __init__(
        self, tracer: "Tracer", name: str, cat: str,
        trace_id: str, parent_id: str | None, args: dict[str, Any],
    ):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.args = args

    def set(self, **args: Any) -> None:
        """Attach/override args after entry (e.g. reply byte counts)."""
        self.args.update(args)

    def __enter__(self) -> "Span":
        self._t0_us = _now_us()
        self._t0 = time.perf_counter()
        self._prev = getattr(_current, "span", None)
        _current.span = self
        return self

    def __exit__(self, et, ev, tb) -> bool:
        # duration from the monotonic clock (wall time can step); start
        # from the wall clock (cross-process alignment)
        dur_us = (time.perf_counter() - self._t0) * 1e6
        _current.span = self._prev
        if et is not None:
            self.args.setdefault("error", repr(ev))
        args = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            **({"parent_id": self.parent_id} if self.parent_id else {}),
            **self.args,
        }
        self._tracer._record({
            "name": self.name,
            "cat": self.cat or "default",
            "ph": "X",
            "ts": self._t0_us,
            "dur": dur_us,
            "pid": os.getpid(),
            "tid": threading.get_native_id(),
            "args": args,
        })
        return False


class _RemoteParent:
    """Wire-borne span context installed by ``activate()``: spans opened
    under it join the remote caller's trace instead of starting one."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id


class _Activation:
    __slots__ = ("_parent", "_prev")

    def __init__(self, parent: _RemoteParent):
        self._parent = parent

    def __enter__(self) -> "_Activation":
        self._prev = getattr(_current, "span", None)
        _current.span = self._parent
        return self

    def __exit__(self, *exc: Any) -> bool:
        _current.span = self._prev
        return False


class Tracer:
    """Span recorder with a Chrome trace-event exporter. One module-global
    instance (``trace.tracer``) serves the process; the module-level
    ``span``/``instant``/... helpers delegate to whatever the global
    currently is, so ``configure()`` can re-arm mid-process."""

    def __init__(
        self,
        trace_dir: str | None = None,
        capacity: int = DEFAULT_CAPACITY,
        process_name: str = "",
        sample: int = 1,
    ):
        self._dir = trace_dir or None
        self._buf: deque[dict[str, Any]] = deque(maxlen=max(capacity, 1))
        self._lock = threading.Lock()
        self.process_name = process_name or f"proc-{os.getpid()}"
        # head-based sampling: record 1 in ``sample`` TRACES, decided
        # once per trace id — every process keyed the same way keeps the
        # same traces, so always-on tracing at production step rates
        # yields whole cross-process traces, never fragments
        self._sample = max(1, int(sample))

    @property
    def enabled(self) -> bool:
        return self._dir is not None

    @property
    def sample(self) -> int:
        return self._sample

    def _keep(self, trace_id: str) -> bool:
        """The head-sampling decision, a pure function of the trace id
        (hex): consistent for every span of one trace in every process."""
        if self._sample <= 1:
            return True
        try:
            return int(trace_id[:8], 16) % self._sample == 0
        except (ValueError, TypeError):
            return True

    @property
    def trace_dir(self) -> str | None:
        return self._dir

    # -- recording --------------------------------------------------------

    def span(self, name: str, cat: str = "", **args: Any):
        """Context manager for one span. Disabled path: returns the
        process-global no-op singleton (no allocation). A trace the head
        sampler drops gets a :class:`_DroppedSpan` instead — nesting and
        propagation intact, nothing recorded."""
        if self._dir is None:
            return _NOOP
        cur = getattr(_current, "span", None)
        if cur is not None and cur.trace_id is not None:
            trace_id, parent = cur.trace_id, cur.span_id
        else:
            trace_id, parent = _new_id(), None
        if not self._keep(trace_id):
            return _DroppedSpan(trace_id)
        return Span(self, name, cat, trace_id, parent, args)

    def instant(self, name: str, cat: str = "", **args: Any) -> None:
        """Point-in-time annotation (retry fired, reconnect started);
        rides the current span's trace when one is live."""
        if self._dir is None:
            return
        cur = getattr(_current, "span", None)
        if cur is not None and cur.trace_id is not None:
            if not self._keep(cur.trace_id):
                return  # the instant belongs to a head-dropped trace
            args = {"trace_id": cur.trace_id, "parent_id": cur.span_id, **args}
        self._record({
            "name": name,
            "cat": cat or "default",
            "ph": "i",
            "ts": _now_us(),
            "s": "t",  # thread-scoped instant
            "pid": os.getpid(),
            "tid": threading.get_native_id(),
            "args": args,
        })

    def counter(self, name: str, value: float, cat: str = "") -> None:
        """Perfetto counter-track sample (Chrome ``"C"`` event): numeric
        series rendered as a stepped counter track next to the spans —
        the histogram-export-as-counter-track form the PR-2 ROADMAP item
        asked for. Used for queue depth and apply-batch size; free when
        tracing is disabled (same contract as ``span``)."""
        if self._dir is None:
            return
        self._record({
            "name": name,
            "cat": cat or "default",
            "ph": "C",
            "ts": _now_us(),
            "pid": os.getpid(),
            "tid": threading.get_native_id(),
            "args": {"value": float(value)},
        })

    def flow_start(
        self, name: str, cat: str = "", flow_id: str | None = None,
        **args: Any,
    ) -> str | None:
        """Open a flow arrow (Chrome ``"s"`` event): the span-link
        primitive for in-flight futures — a later :meth:`flow_end` with
        the same id (on any thread or span) draws the arrow from this
        point to that one in Perfetto, linking a push's issue span to its
        completion. Returns the flow id (None when disabled — callers
        pass it straight back to ``flow_end``, which then no-ops)."""
        if self._dir is None:
            return None
        cur = getattr(_current, "span", None)
        if (
            cur is not None
            and cur.trace_id is not None
            and not self._keep(cur.trace_id)
        ):
            return None  # head-dropped trace: flow_end no-ops on None
        fid = flow_id or _new_id()
        self._record_flow(name, cat, "s", fid, args)
        return fid

    def flow_end(
        self, name: str, cat: str = "", flow_id: str | None = None,
        **args: Any,
    ) -> None:
        """Close a flow arrow opened by ``flow_start`` (Chrome ``"f"``
        event, next-slice binding). No-op when disabled or fed the None
        id a disabled ``flow_start`` returned."""
        if self._dir is None or flow_id is None:
            return
        self._record_flow(name, cat, "f", flow_id, args)

    def _record_flow(
        self, name: str, cat: str, ph: str, fid: str, args: dict[str, Any]
    ) -> None:
        cur = getattr(_current, "span", None)
        if cur is not None and cur.trace_id is not None:
            args = {"trace_id": cur.trace_id, "parent_id": cur.span_id, **args}
        ev: dict[str, Any] = {
            "name": name,
            "cat": cat or "default",
            "ph": ph,
            "id": fid,
            "ts": _now_us(),
            "pid": os.getpid(),
            "tid": threading.get_native_id(),
            "args": args,
        }
        if ph == "f":
            ev["bp"] = "e"  # bind to the enclosing slice at the arrowhead
        self._record(ev)

    def wire_context(self) -> dict[str, str] | None:
        """The current span's identity for an RPC header (``None`` when
        disabled or outside any span — callers skip the header field)."""
        if self._dir is None:
            return None
        cur = getattr(_current, "span", None)
        if cur is None or cur.trace_id is None:
            return None
        return {"tid": cur.trace_id, "sid": cur.span_id}

    def activate(self, ctx: dict[str, str] | None):
        """Server side of propagation: bind a wire context as this
        thread's parent so dispatch spans join the caller's trace."""
        if self._dir is None or not ctx:
            return _NOOP
        return _Activation(_RemoteParent(ctx["tid"], ctx["sid"]))

    def _record(self, ev: dict[str, Any]) -> None:
        with self._lock:
            self._buf.append(ev)

    # -- inspection / export ----------------------------------------------

    def events(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._buf)

    def export(self, path: str) -> str:
        """Write the buffered events as one strict Chrome trace-event JSON
        object (``ts``-sorted, with process/thread ``M`` metadata), the
        format Perfetto's legacy-JSON importer accepts."""
        return write_chrome_trace(
            self.events(), path,
            process_names={os.getpid(): self.process_name},
        )

    def flush(self) -> str | None:
        """Export into the armed trace dir (no-op when disabled or no
        spans were recorded); returns the written path."""
        if self._dir is None:
            return None
        if not self.events():
            return None
        name = f"trace-{self.process_name}-{os.getpid()}.json"
        return self.export(os.path.join(self._dir, name))


#: the process's tracer; armed at import when PS_TRACE_DIR is set so
#: spawned children need no plumbing (the PS_FAULT_PLAN pattern);
#: PS_TRACE_SAMPLE rides along for head sampling
tracer = Tracer(os.environ.get(TRACE_DIR_ENV) or None, sample=_env_sample())

_atexit_armed = False


def _flush_at_exit() -> None:  # pragma: no cover - interpreter teardown
    try:
        tracer.flush()
    except Exception:
        pass


def _arm_atexit() -> None:
    global _atexit_armed
    if not _atexit_armed:
        atexit.register(_flush_at_exit)
        _atexit_armed = True


if tracer.enabled:  # env-armed at import
    _arm_atexit()


def configure(
    trace_dir: str | None,
    capacity: int = DEFAULT_CAPACITY,
    process_name: str = "",
    sample: int = 1,
) -> Tracer:
    """Replace the global tracer (arm with a dir, disarm with ``""``/
    ``None``; ``sample=N`` records 1/N of traces, keyed off the trace
    id). The previous buffer is dropped — configure at process start,
    before instrumented code runs."""
    global tracer
    tracer = Tracer(trace_dir or None, capacity, process_name, sample=sample)
    if tracer.enabled:
        _arm_atexit()
    return tracer


# -- module-level delegates (resolve the CURRENT global at call time, so
# instrumented modules can `from ... import trace` once and still follow
# configure()'s swaps) ------------------------------------------------------


def span(name: str, cat: str = "", **args: Any):
    return tracer.span(name, cat, **args)


def instant(name: str, cat: str = "", **args: Any) -> None:
    tracer.instant(name, cat, **args)


def counter(name: str, value: float, cat: str = "") -> None:
    tracer.counter(name, value, cat)


def flow_start(
    name: str, cat: str = "", flow_id: str | None = None, **args: Any
) -> str | None:
    return tracer.flow_start(name, cat, flow_id, **args)


def flow_end(
    name: str, cat: str = "", flow_id: str | None = None, **args: Any
) -> None:
    tracer.flow_end(name, cat, flow_id, **args)


def wire_context() -> dict[str, str] | None:
    return tracer.wire_context()


def activate(ctx: dict[str, str] | None):
    return tracer.activate(ctx)


def enabled() -> bool:
    return tracer.enabled


def traced(name: str | None = None, cat: str = "") -> Callable:
    """Decorator form of ``span`` (checks the live global per call, so a
    decorated function is free when tracing is off)."""

    def deco(fn: Callable) -> Callable:
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a: Any, **kw: Any):
            if not tracer.enabled:
                return fn(*a, **kw)
            with tracer.span(label, cat=cat):
                return fn(*a, **kw)

        return wrapper

    return deco


def write_chrome_trace(
    events: list[dict[str, Any]],
    path: str,
    process_names: dict[int, str] | None = None,
    thread_names: dict[tuple[int, int], str] | None = None,
) -> str:
    """The exporter's file-writing core, shared with the postmortem
    plane (utils/postmortem.py renders merged blackbox timelines through
    it): ``ts``-sort the events, prepend process/thread ``M`` metadata,
    atomically write one strict Chrome trace-event JSON object — the
    format Perfetto's legacy-JSON importer accepts."""
    events = sorted(events, key=lambda e: e.get("ts", 0))
    meta: list[dict[str, Any]] = []
    for pid, name in sorted((process_names or {}).items()):
        meta.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name},
        })
    thread_names = thread_names or {}
    for pid, tid in sorted(
        {(e["pid"], e["tid"]) for e in events if "tid" in e}
    ):
        meta.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": thread_names.get((pid, tid), f"thread-{tid}")},
        })
    doc = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def merge_trace_dir(trace_dir: str, out_name: str = "trace-merged.json") -> str:
    """Combine every per-process ``trace-*.json`` in ``trace_dir`` into one
    Perfetto-loadable file (distinct pids keep processes as separate
    tracks). Returns the merged file's path."""
    events: list[dict[str, Any]] = []
    for fn in sorted(os.listdir(trace_dir)):
        if not (fn.startswith("trace-") and fn.endswith(".json")):
            continue
        if fn == out_name:
            continue
        with open(os.path.join(trace_dir, fn)) as f:
            doc = json.load(f)
        events.extend(doc.get("traceEvents", []))
    # stable cross-process ordering: metadata first, then by timestamp
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    out = os.path.join(trace_dir, out_name)
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    os.replace(tmp, out)
    return out
