"""Distributed tracing: spans across every process in the pod.

Reference analog: the reference scheduler was a live dashboard fed by
Progress protos and heartbeat stats, but "where did this step's 40 ms go"
needed per-node timelines the reference never had. This module is that
timeline: a low-overhead :class:`Tracer` whose spans export as Chrome
trace-event JSON (load the file — or the merged file from
:func:`merge_trace_dir` — at https://ui.perfetto.dev), with
trace-id/parent-span propagation carried in the RPC header so one logical
``push`` renders as client-span -> server-dispatch-span -> updater-span
across processes.

Design constraints, in order:

1. **Disabled is free.** The default tracer is disabled; ``span()`` then
   returns one process-global no-op singleton — no Span object, no dict,
   no buffer append, nothing for the GC (tests assert the identity).
   Instrumentation can therefore live permanently on hot paths.
2. **Bounded.** Armed tracing records into a ring buffer
   (``deque(maxlen=capacity)``): a week-long run keeps the newest spans
   and never grows without bound.
3. **Cross-process by construction.** ``ts`` is wall-clock microseconds
   (the only clock two processes share), ``pid``/``tid`` are real OS ids,
   and every span carries ``trace_id``/``span_id``/``parent_id`` in its
   ``args`` so the RPC layer can stitch client and server timelines.

Arming (same inheritance pattern as ``PS_FAULT_PLAN``): the
``PS_TRACE_DIR`` env var arms the import-time global tracer — spawned
multihost children inherit it for free; ``configure()`` re-arms
explicitly (CLI ``--trace_dir`` / config ``[trace] trace_dir``). Each
armed process writes ``trace-<name>-<pid>.json`` into the directory at
exit (atexit backstop) or on ``tracer.flush()``.

**Tail-biased capture** (:class:`TailCapture`, ISSUE 15): head sampling
(``sample=1/N``) keeps 1/N of traces by trace-id hash — which
statistically drops exactly the slow traces worth keeping. With tail
capture armed, a head-DROPPED trace's spans are buffered per trace
until the trace completes, and the completed trace is **promoted** to
the export ring when it (a) lands in the slowest-K per root-span name
for the current window, (b) carries anomaly events (rpc.retry /
rpc.reconnect / an errored span), or (c) breaches the live windowed p99
of its root name (the PR-2 log2 histogram machinery). Promotion
overrides the head-sampling drop decision; unpromoted traces fall into
a bounded limbo ring exported as a ``tracetail-*.json`` sidecar, so a
trace another process promoted (the slow half of a cross-process push)
can be rescued at merge/analysis time (``merge_trace_dir`` pulls
sidecar events whose trace id appears in any main file). Memory is
bounded everywhere (pending-trace count, events per trace, limbo ring);
with tracing off the whole layer is the same identity-pinned no-op
path as ever.

API sketch::

    from parameter_server_tpu.utils import trace

    with trace.span("step.pull", cat="step", bytes=n):   # context manager
        ...
    @trace.traced("load_shard")                          # decorator
    def load_shard(...): ...
    trace.instant("rpc.retry", addr=addr)                # point event

    header["_trace"] = trace.wire_context()              # client side
    with trace.activate(header.pop("_trace", None)):     # server side
        ...spans here join the caller's trace...
"""

from __future__ import annotations

import atexit
import functools
import json
import os
import random
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable

TRACE_DIR_ENV = "PS_TRACE_DIR"
TRACE_SAMPLE_ENV = "PS_TRACE_SAMPLE"
TRACE_TAIL_ENV = "PS_TRACE_TAIL"

#: ring-buffer default: ~64k spans x ~200 B/event ~= 13 MB ceiling per process
DEFAULT_CAPACITY = 65536

#: tail-capture defaults (see TailCapture): slowest-K per root name kept
#: per window, limbo sidecar ring bound, pending-trace bounds
DEFAULT_TAIL_K = 4
DEFAULT_TAIL_LIMBO = 8192

#: instant-event names whose presence promotes the enclosing trace (the
#: "anomaly-bearing" leg of the tail-promotion policy); errored spans
#: (an ``error`` arg) promote through the same gate
TAIL_ANOMALY_EVENTS = frozenset({"rpc.retry", "rpc.reconnect"})


def _env_sample() -> int:
    try:
        return max(1, int(os.environ.get(TRACE_SAMPLE_ENV, "1") or 1))
    except ValueError:
        return 1


def _env_tail_k() -> int:
    """PS_TRACE_TAIL: the slowest-K bound for env-armed processes
    (spawned children). Unset/empty/"1" = the default K armed; "0"
    disarms tail capture; any other int = that K."""
    raw = os.environ.get(TRACE_TAIL_ENV, "")
    if raw in ("", "1"):
        return DEFAULT_TAIL_K
    try:
        return max(0, int(raw))
    except ValueError:
        return DEFAULT_TAIL_K

_current = threading.local()  # .span: innermost live span (or remote parent)


def _now_us() -> float:
    """Wall-clock microseconds: the only timebase two processes share, so
    Perfetto lines up client and server spans on one axis."""
    return time.time() * 1e6


#: id generator: urandom-seeded Mersenne stream, NOT uuid4 — uuid4 hits
#: posix.urandom per call (~12 us), which at two ids per span was the
#: single largest cost of armed tracing on the push hot path. One C
#: getrandbits call under the GIL is atomic enough for id draws.
_id_rng = random.Random()


def _new_id() -> str:
    return f"{_id_rng.getrandbits(64):016x}"


#: cached OS identities for the per-event stamps: on sandboxed/para-
#: virtualized kernels getpid/gettid are full-priced syscalls (~15 us
#: here), and every recorded event stamps both. The pid refreshes on
#: fork; the native thread id is cached per thread in the existing
#: thread-local.
_pid = os.getpid()


def _refresh_pid() -> None:  # pragma: no cover - fork path
    global _pid
    _pid = os.getpid()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_refresh_pid)


def _tid() -> int:
    t = getattr(_current, "tid", None)
    if t is None:
        t = _current.tid = threading.get_native_id()
    return t


class _NoopSpan:
    """The disabled-path singleton: enter/exit/set are all no-ops and no
    instance is ever allocated per call — ``Tracer.span`` returns THIS
    object every time when tracing is off (the "tracing disabled is
    free" contract, asserted by tests)."""

    __slots__ = ()
    trace_id = None
    span_id = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set(self, **args: Any) -> None:
        pass


_NOOP = _NoopSpan()


class _DroppedSpan:
    """A span inside a head-DROPPED trace (``sample=1/N``): it keeps the
    thread-local nesting and a real wire identity — descendants, instants
    and remote callees all see the shared trace id and make the SAME drop
    decision, so sampling keeps whole traces or none of one — but records
    nothing into the buffer."""

    __slots__ = ("trace_id", "span_id", "_prev")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.span_id = _new_id()

    def set(self, **args: Any) -> None:
        pass

    def __enter__(self) -> "_DroppedSpan":
        self._prev = getattr(_current, "span", None)
        _current.span = self
        return self

    def __exit__(self, *exc: Any) -> bool:
        _current.span = self._prev
        return False


class Span:
    """One live span (context manager). Recorded as a Chrome ``"X"``
    (complete) event on exit; nesting via a thread-local stack gives
    parent ids without any caller plumbing."""

    __slots__ = (
        "_tracer", "name", "cat", "trace_id", "span_id", "parent_id",
        "args", "_t0_us", "_t0", "_prev", "_tail_seal",
    )

    def __init__(
        self, tracer: "Tracer", name: str, cat: str,
        trace_id: str, parent_id: str | None, args: dict[str, Any],
    ):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.args = args
        # set by Tracer.span for the LOCAL ROOT span of a head-dropped
        # trace under tail capture: its exit seals the trace (promotion
        # decision) — flag-driven, so a single-span trace (the RPC hot
        # path's common case) never touches the pending table at all
        self._tail_seal = False

    def set(self, **args: Any) -> None:
        """Attach/override args after entry (e.g. reply byte counts)."""
        self.args.update(args)

    def __enter__(self) -> "Span":
        self._t0_us = _now_us()
        self._t0 = time.perf_counter()
        self._prev = getattr(_current, "span", None)
        _current.span = self
        return self

    def __exit__(self, et, ev, tb) -> bool:
        # duration from the monotonic clock (wall time can step); start
        # from the wall clock (cross-process alignment)
        dur_us = (time.perf_counter() - self._t0) * 1e6
        _current.span = self._prev
        if et is not None:
            self.args.setdefault("error", repr(ev))
        args = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            **({"parent_id": self.parent_id} if self.parent_id else {}),
            **self.args,
        }
        self._tracer._record(
            {
                "name": self.name,
                "cat": self.cat or "default",
                "ph": "X",
                "ts": self._t0_us,
                "dur": dur_us,
                "pid": _pid,
                "tid": _tid(),
                "args": args,
            },
            tail_seal=self._tail_seal,
        )
        return False


class _RemoteParent:
    """Wire-borne span context installed by ``activate()``: spans opened
    under it join the remote caller's trace instead of starting one."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id


class _Activation:
    __slots__ = ("_parent", "_prev")

    def __init__(self, parent: _RemoteParent):
        self._parent = parent

    def __enter__(self) -> "_Activation":
        self._prev = getattr(_current, "span", None)
        _current.span = self._parent
        return self

    def __exit__(self, *exc: Any) -> bool:
        _current.span = self._prev
        return False


class _PendingTrace:
    """One head-dropped trace buffered until completion (tail capture).
    Created LAZILY by the first non-root event — a single-span trace
    (the RPC hot path's common case) seals straight from its root exit
    and never allocates one."""

    __slots__ = ("events", "anomaly", "truncated")

    def __init__(self) -> None:
        self.events: list[dict[str, Any]] = []
        self.anomaly = False
        self.truncated = 0


class TailCapture:
    """The tail-retention layer (ISSUE 15): completion-time promotion of
    head-dropped traces.

    Head sampling decides keep/drop at trace START, so the slowest
    traces — the ones worth keeping — die before anyone knows they are
    slow. With this layer armed, a dropped trace's events buffer in a
    per-trace pending list; when its (locally) root span exits, the
    whole trace is judged at once:

    - **slowest-K**: the root duration ranks in the top ``k`` for its
      root-span name within the current window;
    - **anomaly-bearing**: the trace carries a
      :data:`TAIL_ANOMALY_EVENTS` instant or an errored span;
    - **p99 breach**: the root duration exceeds the live windowed p99
      of its name (per-name PR-2 log2 histograms, windowed by snapshot
      deltas — the same discipline the time-series plane uses).

    Promoted traces move into the tracer's export ring (overriding the
    head-sampling drop) and fire a ``trace.promote`` flight-recorder
    event; unpromoted ones land in a bounded **limbo** ring exported as
    a ``tracetail-*.json`` sidecar so a cross-process trace promoted by
    ANOTHER process (the client saw the tail latency; this server's
    segment looked fast locally) is rescued at merge/analysis time.

    Every structure is bounded: at most ``max_pending`` open traces
    (the oldest is sealed unpromoted on overflow), ``max_events`` per
    trace (extra events are counted, not kept), ``limbo_events`` limbo
    entries, and K + one ~40-int histogram per distinct root name."""

    _RECENT = 512  # sealed-verdict memory: late events still route right

    def __init__(
        self,
        k: int = DEFAULT_TAIL_K,
        limbo_events: int = DEFAULT_TAIL_LIMBO,
        max_pending: int = 256,
        max_events: int = 256,
        window_s: float = 30.0,
        min_window_count: int = 32,
    ):
        self.k = max(0, int(k))
        self.window_s = float(window_s)
        self.min_window_count = int(min_window_count)
        self.max_pending = max(1, int(max_pending))
        self.max_events = max(8, int(max_events))
        self._pending: "OrderedDict[str, _PendingTrace]" = OrderedDict()
        self._recent: "OrderedDict[str, bool]" = OrderedDict()
        self._limbo: deque[dict[str, Any]] = deque(
            maxlen=max(int(limbo_events), 64)
        )
        # per-root-name windowed stats: top-K durations + a log2
        # histogram (utils/metrics.py machinery) with a baseline
        # snapshot stashed at each window roll, so the p99 read is the
        # DELTA percentile — the live windowed p99, not since-boot
        self._top: dict[str, list[float]] = {}
        self._hists: dict[str, Any] = {}
        self._base: dict[str, dict[str, Any]] = {}
        # per-name p99 read cache: the delta-percentile read (snapshot
        # + bucket walk) is the seal path's priciest step; at hot-path
        # seal rates it is refreshed at most every _P99_TTL_S per name
        # (a slightly stale threshold only shifts WHICH borderline
        # trace promotes — the slowest-K gate is exact regardless)
        self._p99_cache: dict[str, tuple[float, float | None]] = {}
        self._window_start = time.monotonic()
        self._lock = threading.Lock()

    _P99_TTL_S = 0.25

    # -- stats -------------------------------------------------------------

    def _roll_window_locked(self) -> None:
        now = time.monotonic()
        if now - self._window_start < self.window_s:
            return
        self._window_start = now
        self._top.clear()
        self._p99_cache.clear()
        self._base = {k: h.snapshot() for k, h in self._hists.items()}

    def _windowed_p99_locked(self, name: str) -> float | None:
        from parameter_server_tpu.utils.metrics import hist_percentile

        h = self._hists.get(name)
        if h is None:
            return None
        snap = h.snapshot()
        base = self._base.get(name)
        if base:
            snap = {
                "count": snap["count"] - base.get("count", 0),
                "buckets": {
                    k: c - base.get("buckets", {}).get(k, 0)
                    for k, c in snap.get("buckets", {}).items()
                },
            }
        if snap.get("count", 0) < self.min_window_count:
            return None
        return hist_percentile(snap, 0.99)

    def _p99_cached_locked(self, name: str) -> float | None:
        now = time.monotonic()
        hit = self._p99_cache.get(name)
        if hit is not None and hit[0] > now:
            return hit[1]
        p99 = self._windowed_p99_locked(name)
        self._p99_cache[name] = (now + self._P99_TTL_S, p99)
        return p99

    def observe_root(self, name: str, dur_s: float) -> None:
        """Feed one completed root span into the windowed stats (kept
        and dropped traces alike — the promotion thresholds must see
        the whole population, not just the sampled-out slice)."""
        with self._lock:
            self._observe_root_locked(name, dur_s)

    def _observe_root_locked(self, name: str, dur_s: float) -> None:
        from parameter_server_tpu.utils.metrics import Histogram

        self._roll_window_locked()
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = Histogram()
        top = self._top.setdefault(name, [])
        top.append(dur_s)
        top.sort(reverse=True)
        del top[self.k:]
        h.observe(dur_s)  # Histogram's own lock is a leaf under ours

    # -- pending-trace lifecycle ------------------------------------------

    def _remember_locked(self, trace_id: str, promoted: bool) -> None:
        self._recent[trace_id] = promoted
        while len(self._recent) > self._RECENT:
            self._recent.popitem(last=False)

    def _open_locked(self, trace_id: str) -> _PendingTrace:
        while len(self._pending) >= self.max_pending:
            # overflow: the oldest pending trace seals unpromoted (its
            # root span leaked or is very long-lived)
            _t, old = self._pending.popitem(last=False)
            self._limbo.extend(old.events)
            self._remember_locked(_t, False)
        pend = self._pending[trace_id] = _PendingTrace()
        return pend

    def route(self, trace_id: str, ev: dict[str, Any], tracer: "Tracer") -> bool:
        """Destination decision for one recorded NON-sealing event of
        ``trace_id``; True = consumed here (pending buffer or limbo),
        False = the caller records it into the main ring. Root-span
        exits of KEPT traces pass through but feed the windowed stats;
        a head-dropped trace's first buffered event creates its pending
        entry lazily (local-root exits go through :meth:`seal_event`
        instead — flag-driven by the span layer).

        Everything runs under ONE lock acquisition: events for one
        trace arrive from several threads (the serve thread's dispatch
        exit vs the apply thread's updater marker), and a buffer append
        racing the seal would strand the event in an already-flushed
        list, silently losing it from both ring and sidecar."""
        args = ev.get("args") or {}
        with self._lock:
            pend = self._pending.get(trace_id)
            if pend is None:
                verdict = self._recent.get(trace_id)
                if verdict is not None:
                    if verdict:
                        return False  # promoted: late events join the ring
                    self._limbo.append(ev)
                    return True
                if tracer._keep(trace_id):
                    # a head-KEPT trace — record normally, observing
                    # parentless root completions into the stats
                    if ev.get("ph") == "X" and "parent_id" not in args:
                        self._observe_root_locked(
                            ev["name"], ev.get("dur", 0.0) / 1e6
                        )
                    return False
                pend = self._open_locked(trace_id)
            if (
                ev.get("ph") == "i" and ev["name"] in TAIL_ANOMALY_EVENTS
            ) or "error" in args:
                pend.anomaly = True
            if len(pend.events) >= self.max_events:
                pend.truncated += 1
            else:
                pend.events.append(ev)
            return True

    def seal_event(
        self, trace_id: str, root_ev: dict[str, Any], tracer: "Tracer"
    ) -> bool:
        """A head-dropped trace's LOCAL ROOT span exited (the span layer
        flags it): judge the whole trace — buffered children plus this
        root event, which ALWAYS keeps its slot (a promoted trace
        exported without its root would be unstitchable by the
        critical-path engine). True = consumed (promoted to the ring as
        a batch, or limbo'd); False = late root of an already-promoted
        trace, caller records it into the ring."""
        args = root_ev.get("args") or {}
        name = root_ev["name"]
        dur_s = root_ev.get("dur", 0.0) / 1e6
        why = None
        promoted_events: list[dict[str, Any]] | None = None
        with self._lock:
            verdict = self._recent.get(trace_id)
            if verdict is not None:
                # a second local root (e.g. the apply thread's updater
                # marker after the dispatch span sealed): late event
                if verdict:
                    return False
                self._limbo.append(root_ev)
                return True
            pend = self._pending.pop(trace_id, None)
            events = pend.events if pend is not None else []
            events.append(root_ev)
            anomaly = (
                pend.anomaly if pend is not None else False
            ) or "error" in args
            self._roll_window_locked()
            if anomaly:
                why = "anomaly"
            else:
                top = self._top.get(name) or []
                if self.k > 0 and (len(top) < self.k or dur_s > top[-1]):
                    why = "slowk"
                else:
                    p99 = self._p99_cached_locked(name)
                    if p99 is not None and dur_s > p99:
                        why = "p99"
            self._observe_root_locked(name, dur_s)
            self._remember_locked(trace_id, why is not None)
            if why is None:
                self._limbo.extend(events)
            else:
                promoted_events = events
        # counters / ring append / flightrec OUTSIDE the tail lock
        from parameter_server_tpu.utils.metrics import wire_counters

        if promoted_events is None:
            wire_counters.inc("trace_tail_dropped")
            return True
        tracer._append_events(promoted_events)
        wire_counters.inc("trace_tail_promoted")
        from parameter_server_tpu.utils import flightrec

        flightrec.record(
            "trace.promote", cmd=name, tid=trace_id, why=why,
            dur_ms=round(dur_s * 1e3, 3),
        )
        return True

    def limbo_events(self) -> list[dict[str, Any]]:
        """Snapshot of the unpromoted-trace ring (the sidecar's body)."""
        with self._lock:
            return list(self._limbo)


class Tracer:
    """Span recorder with a Chrome trace-event exporter. One module-global
    instance (``trace.tracer``) serves the process; the module-level
    ``span``/``instant``/... helpers delegate to whatever the global
    currently is, so ``configure()`` can re-arm mid-process."""

    def __init__(
        self,
        trace_dir: str | None = None,
        capacity: int = DEFAULT_CAPACITY,
        process_name: str = "",
        sample: int = 1,
        tail: TailCapture | None = None,
    ):
        self._dir = trace_dir or None
        self._buf: deque[dict[str, Any]] = deque(maxlen=max(capacity, 1))
        self._lock = threading.Lock()
        self.process_name = process_name or f"proc-{os.getpid()}"
        # head-based sampling: record 1 in ``sample`` TRACES, decided
        # once per trace id — every process keyed the same way keeps the
        # same traces, so always-on tracing at production step rates
        # yields whole cross-process traces, never fragments
        self._sample = max(1, int(sample))
        # tail-biased retention (ISSUE 15): with this armed, the head
        # sampler's drop verdict becomes provisional — see TailCapture
        self._tail = tail if self._dir is not None else None

    @property
    def enabled(self) -> bool:
        return self._dir is not None

    @property
    def sample(self) -> int:
        return self._sample

    def _keep(self, trace_id: str) -> bool:
        """The head-sampling decision, a pure function of the trace id
        (hex): consistent for every span of one trace in every process."""
        if self._sample <= 1:
            return True
        try:
            # psl: ignore[idtype]: head-sampling hashes the id's hex prefix by design — the one sanctioned place a trace id acts numeric
            return int(trace_id[:8], 16) % self._sample == 0
        except (ValueError, TypeError):
            return True

    @property
    def trace_dir(self) -> str | None:
        return self._dir

    @property
    def tail(self) -> TailCapture | None:
        """The armed tail-capture layer (None when off)."""
        return self._tail

    # -- recording --------------------------------------------------------

    def span(self, name: str, cat: str = "", **args: Any):
        """Context manager for one span. Disabled path: returns the
        process-global no-op singleton (no allocation). A trace the head
        sampler drops gets a :class:`_DroppedSpan` instead — nesting and
        propagation intact, nothing recorded — UNLESS tail capture is
        armed, in which case the span records into the trace's pending
        buffer and the keep/drop verdict waits for trace completion
        (TailCapture: promotion overrides the head drop)."""
        if self._dir is None:
            return _NOOP
        cur = getattr(_current, "span", None)
        if cur is not None and cur.trace_id is not None:
            trace_id, parent = cur.trace_id, cur.span_id
        else:
            trace_id, parent = _new_id(), None
        if not self._keep(trace_id):
            tail = self._tail
            if tail is None:
                return _DroppedSpan(trace_id)
            sp = Span(self, name, cat, trace_id, parent, args)
            # the LOCAL root (trace started here, or entered via a
            # remote activation) seals the trace at exit; nested local
            # spans just buffer
            sp._tail_seal = cur is None or isinstance(cur, _RemoteParent)
            return sp
        return Span(self, name, cat, trace_id, parent, args)

    def instant(
        self, name: str, cat: str = "",
        ctx: dict[str, str] | None = None, **args: Any,
    ) -> None:
        """Point-in-time annotation (retry fired, reconnect started);
        rides the current span's trace when one is live. ``ctx`` binds
        an EXPLICIT wire context instead — for emitters on threads with
        no live span acting on another trace's behalf (the heal marks
        every stranded pending call's trace, so the tail-capture
        anomaly gate sees the reconnect the trace actually absorbed)."""
        if self._dir is None:
            return
        if ctx:
            if not self._keep(ctx["tid"]) and self._tail is None:
                return  # head-dropped trace, no tail layer to buffer it
            args = {"trace_id": ctx["tid"], "parent_id": ctx["sid"], **args}
        elif (cur := getattr(_current, "span", None)) is not None and (
            cur.trace_id is not None
        ):
            if not self._keep(cur.trace_id) and self._tail is None:
                return  # head-dropped trace, no tail layer to buffer it
            args = {"trace_id": cur.trace_id, "parent_id": cur.span_id, **args}
        self._record({
            "name": name,
            "cat": cat or "default",
            "ph": "i",
            "ts": _now_us(),
            "s": "t",  # thread-scoped instant
            "pid": _pid,
            "tid": _tid(),
            "args": args,
        })

    def counter(self, name: str, value: float, cat: str = "") -> None:
        """Perfetto counter-track sample (Chrome ``"C"`` event): numeric
        series rendered as a stepped counter track next to the spans —
        the histogram-export-as-counter-track form the PR-2 ROADMAP item
        asked for. Used for queue depth and apply-batch size; free when
        tracing is disabled (same contract as ``span``)."""
        if self._dir is None:
            return
        self._record({
            "name": name,
            "cat": cat or "default",
            "ph": "C",
            "ts": _now_us(),
            "pid": _pid,
            "tid": _tid(),
            "args": {"value": float(value)},
        })

    def flow_start(
        self, name: str, cat: str = "", flow_id: str | None = None,
        **args: Any,
    ) -> str | None:
        """Open a flow arrow (Chrome ``"s"`` event): the span-link
        primitive for in-flight futures — a later :meth:`flow_end` with
        the same id (on any thread or span) draws the arrow from this
        point to that one in Perfetto, linking a push's issue span to its
        completion. Returns the flow id (None when disabled — callers
        pass it straight back to ``flow_end``, which then no-ops)."""
        if self._dir is None:
            return None
        cur = getattr(_current, "span", None)
        if (
            cur is not None
            and cur.trace_id is not None
            and not self._keep(cur.trace_id)
            and self._tail is None
        ):
            return None  # head-dropped trace: flow_end no-ops on None
        fid = flow_id or _new_id()
        self._record_flow(name, cat, "s", fid, args)
        return fid

    def flow_end(
        self, name: str, cat: str = "", flow_id: str | None = None,
        **args: Any,
    ) -> None:
        """Close a flow arrow opened by ``flow_start`` (Chrome ``"f"``
        event, next-slice binding). No-op when disabled or fed the None
        id a disabled ``flow_start`` returned."""
        if self._dir is None or flow_id is None:
            return
        self._record_flow(name, cat, "f", flow_id, args)

    def _record_flow(
        self, name: str, cat: str, ph: str, fid: str, args: dict[str, Any]
    ) -> None:
        cur = getattr(_current, "span", None)
        if cur is not None and cur.trace_id is not None:
            args = {"trace_id": cur.trace_id, "parent_id": cur.span_id, **args}
        ev: dict[str, Any] = {
            "name": name,
            "cat": cat or "default",
            "ph": ph,
            "id": fid,
            "ts": _now_us(),
            "pid": _pid,
            "tid": _tid(),
            "args": args,
        }
        if ph == "f":
            ev["bp"] = "e"  # bind to the enclosing slice at the arrowhead
        self._record(ev)

    def wire_context(self) -> dict[str, str] | None:
        """The current span's identity for an RPC header (``None`` when
        disabled or outside any span — callers skip the header field)."""
        if self._dir is None:
            return None
        cur = getattr(_current, "span", None)
        if cur is None or cur.trace_id is None:
            return None
        return {"tid": cur.trace_id, "sid": cur.span_id}

    def activate(self, ctx: dict[str, str] | None):
        """Server side of propagation: bind a wire context as this
        thread's parent so dispatch spans join the caller's trace."""
        if self._dir is None or not ctx:
            return _NOOP
        return _Activation(_RemoteParent(ctx["tid"], ctx["sid"]))

    def _record(self, ev: dict[str, Any], tail_seal: bool = False) -> None:
        tail = self._tail
        if tail is not None:
            tid = (ev.get("args") or {}).get("trace_id")
            # tail routing happens BEFORE the ring lock (TailCapture
            # takes its own lock and may call _append_events, which
            # takes the ring lock — one consistent order: tail -> ring)
            if tid is not None:
                if tail_seal:
                    if tail.seal_event(tid, ev, self):
                        return
                elif tail.route(tid, ev, self):
                    return
        with self._lock:
            self._buf.append(ev)

    def _append_events(self, evs: list[dict[str, Any]]) -> None:
        """Bulk ring append (the tail layer's promotion path)."""
        with self._lock:
            self._buf.extend(evs)

    # -- inspection / export ----------------------------------------------

    def events(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._buf)

    def export(self, path: str) -> str:
        """Write the buffered events as one strict Chrome trace-event JSON
        object (``ts``-sorted, with process/thread ``M`` metadata), the
        format Perfetto's legacy-JSON importer accepts."""
        return write_chrome_trace(
            self.events(), path,
            process_names={os.getpid(): self.process_name},
        )

    def flush(self) -> str | None:
        """Export into the armed trace dir (no-op when disabled or no
        spans were recorded); returns the written path. With tail
        capture armed, the limbo ring (completed-but-unpromoted traces)
        also lands as a ``tracetail-*.json`` sidecar — the raw material
        ``merge_trace_dir`` / the critical-path engine rescue when some
        OTHER process promoted one of those traces."""
        if self._dir is None:
            return None
        tail = self._tail
        if tail is not None:
            limbo = tail.limbo_events()
            if limbo:
                write_chrome_trace(
                    limbo,
                    os.path.join(
                        self._dir,
                        f"tracetail-{self.process_name}-{os.getpid()}.json",
                    ),
                    process_names={os.getpid(): self.process_name},
                )
        if not self.events():
            return None
        name = f"trace-{self.process_name}-{os.getpid()}.json"
        return self.export(os.path.join(self._dir, name))


#: the process's tracer; armed at import when PS_TRACE_DIR is set so
#: spawned children need no plumbing (the PS_FAULT_PLAN pattern);
#: PS_TRACE_SAMPLE rides along for head sampling and PS_TRACE_TAIL for
#: tail capture (on by default for env-armed processes: always-on
#: tail-biased retention is the point of arming a production run)
tracer = Tracer(
    os.environ.get(TRACE_DIR_ENV) or None,
    sample=_env_sample(),
    # tail capture only matters when head sampling can DROP something:
    # at sample=1 every trace is kept and promotion is unreachable, so
    # arming the layer would add per-event routing for zero benefit
    tail=(
        TailCapture(k=_env_tail_k())
        if _env_tail_k() > 0 and _env_sample() > 1
        else None
    ),
)

_atexit_armed = False


def _flush_at_exit() -> None:  # pragma: no cover - interpreter teardown
    try:
        tracer.flush()
    except Exception:
        pass


def _arm_atexit() -> None:
    global _atexit_armed
    if not _atexit_armed:
        atexit.register(_flush_at_exit)
        _atexit_armed = True


if tracer.enabled:  # env-armed at import
    _arm_atexit()


def configure(
    trace_dir: str | None,
    capacity: int = DEFAULT_CAPACITY,
    process_name: str = "",
    sample: int = 1,
    tail: bool = False,
    tail_k: int = DEFAULT_TAIL_K,
    tail_limbo: int = DEFAULT_TAIL_LIMBO,
) -> Tracer:
    """Replace the global tracer (arm with a dir, disarm with ``""``/
    ``None``; ``sample=N`` records 1/N of traces, keyed off the trace
    id; ``tail=True`` arms tail-biased retention — head-dropped traces
    buffer until completion and promote on slowest-K / anomaly / p99
    breach instead of dying at the sampler; a no-op at ``sample=1``,
    where nothing is ever head-dropped and the layer would only add
    per-event routing cost). The previous buffer is dropped — configure
    at process start, before instrumented code runs."""
    global tracer
    tracer = Tracer(
        trace_dir or None, capacity, process_name, sample=sample,
        tail=(
            TailCapture(k=tail_k, limbo_events=tail_limbo)
            if tail and tail_k > 0 and sample > 1
            else None
        ),
    )
    if tracer.enabled:
        _arm_atexit()
    return tracer


# -- module-level delegates (resolve the CURRENT global at call time, so
# instrumented modules can `from ... import trace` once and still follow
# configure()'s swaps) ------------------------------------------------------


def span(name: str, cat: str = "", **args: Any):
    return tracer.span(name, cat, **args)


def instant(
    name: str, cat: str = "", ctx: dict[str, str] | None = None,
    **args: Any,
) -> None:
    tracer.instant(name, cat, ctx=ctx, **args)


def counter(name: str, value: float, cat: str = "") -> None:
    tracer.counter(name, value, cat)


def flow_start(
    name: str, cat: str = "", flow_id: str | None = None, **args: Any
) -> str | None:
    return tracer.flow_start(name, cat, flow_id, **args)


def flow_end(
    name: str, cat: str = "", flow_id: str | None = None, **args: Any
) -> None:
    tracer.flow_end(name, cat, flow_id, **args)


def wire_context() -> dict[str, str] | None:
    return tracer.wire_context()


def activate(ctx: dict[str, str] | None):
    return tracer.activate(ctx)


def enabled() -> bool:
    return tracer.enabled


def traced(name: str | None = None, cat: str = "") -> Callable:
    """Decorator form of ``span`` (checks the live global per call, so a
    decorated function is free when tracing is off)."""

    def deco(fn: Callable) -> Callable:
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a: Any, **kw: Any):
            if not tracer.enabled:
                return fn(*a, **kw)
            with tracer.span(label, cat=cat):
                return fn(*a, **kw)

        return wrapper

    return deco


def write_chrome_trace(
    events: list[dict[str, Any]],
    path: str,
    process_names: dict[int, str] | None = None,
    thread_names: dict[tuple[int, int], str] | None = None,
) -> str:
    """The exporter's file-writing core, shared with the postmortem
    plane (utils/postmortem.py renders merged blackbox timelines through
    it): ``ts``-sort the events, prepend process/thread ``M`` metadata,
    atomically write one strict Chrome trace-event JSON object — the
    format Perfetto's legacy-JSON importer accepts."""
    events = sorted(events, key=lambda e: e.get("ts", 0))
    meta: list[dict[str, Any]] = []
    for pid, name in sorted((process_names or {}).items()):
        meta.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name},
        })
    thread_names = thread_names or {}
    for pid, tid in sorted(
        {(e["pid"], e["tid"]) for e in events if "tid" in e}
    ):
        meta.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": thread_names.get((pid, tid), f"thread-{tid}")},
        })
    doc = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def read_trace_dir(
    trace_dir: str, out_name: str = "trace-merged.json"
) -> tuple[list[dict[str, Any]], list[dict[str, Any]]]:
    """The capture-dir reader shared by :func:`merge_trace_dir` and the
    critical-path engine: ``(main_events, sidecar_events)`` from the
    ``trace-*.json`` main files and ``tracetail-*.json`` tail-capture
    sidecars (the merged file and torn/foreign files are skipped — a
    postmortem works with whatever survived)."""
    main: list[dict[str, Any]] = []
    side: list[dict[str, Any]] = []
    for fn in sorted(os.listdir(trace_dir)):
        if not fn.endswith(".json") or fn == out_name:
            continue
        if fn.startswith("trace-"):
            bucket = main
        elif fn.startswith("tracetail-"):
            bucket = side
        else:
            continue
        try:
            with open(os.path.join(trace_dir, fn)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        bucket.extend(doc.get("traceEvents", []))
    return main, side


def rescue_sidecar_events(
    main: list[dict[str, Any]], side: list[dict[str, Any]]
) -> list[dict[str, Any]]:
    """The cross-process rescue rule, in ONE place: sidecar (limbo)
    events join the capture iff some main file retained their trace id
    — the process that saw the tail latency promoted the trace; the
    processes whose segments looked fast locally only limbo'd theirs.
    ``M`` metadata rides along unconditionally (harmless duplicates)."""
    if not side:
        return []
    promoted = {
        (e.get("args") or {}).get("trace_id") for e in main
    } - {None}
    return [
        e for e in side
        if e.get("ph") == "M"
        or (e.get("args") or {}).get("trace_id") in promoted
    ]


def merge_trace_dir(trace_dir: str, out_name: str = "trace-merged.json") -> str:
    """Combine every per-process ``trace-*.json`` in ``trace_dir`` into one
    Perfetto-loadable file (distinct pids keep processes as separate
    tracks), with ``tracetail-*.json`` sidecar events rescued per
    :func:`rescue_sidecar_events`. Returns the merged file's path."""
    events, sidecar = read_trace_dir(trace_dir, out_name)
    events.extend(rescue_sidecar_events(events, sidecar))
    # stable cross-process ordering: metadata first, then by timestamp
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    out = os.path.join(trace_dir, out_name)
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    os.replace(tmp, out)
    return out
