"""Host process environment helpers.

One home for the "make this process (tree) CPU-only" recipe: besides
``JAX_PLATFORMS``, ambient site hooks keyed off env vars may claim the
host's accelerator at interpreter start (wedging or serializing spawned
children against each other), so those triggers must be dropped wherever
CPU-only children are spawned — the bench fallback and the local
multi-process launcher both use this.
"""

from __future__ import annotations

from typing import MutableMapping

# env vars that arm ambient accelerator-claiming site hooks
AMBIENT_ACCELERATOR_HOOK_VARS = ("PALLAS_AXON_POOL_IPS",)


def force_cpu(env: MutableMapping[str, str]) -> MutableMapping[str, str]:
    """Pin ``env`` (e.g. ``os.environ`` or a child env dict) to the CPU
    backend and disarm known ambient accelerator hooks. Returns ``env``.

    Note: if jax was already imported in this process, also run
    ``jax.config.update("jax_platforms", "cpu")`` — an early import freezes
    the platform default from the pre-call environment."""
    env["JAX_PLATFORMS"] = "cpu"
    for var in AMBIENT_ACCELERATOR_HOOK_VARS:
        env.pop(var, None)
    return env
