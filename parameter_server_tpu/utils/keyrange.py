"""Half-open key ranges and shard math (reference analog: src/util/range.h).

The reference's ``Range<K>`` carries ``[begin, end)`` and ``EvenDivide(n)``;
servers own one range each and every keyed message is sliced against the
server ranges (ref: src/system/executor.* slicing via parallel_ordered_match).

Here ranges describe how the dense hashed key space ``[0, num_keys)`` is
laid out across the ``kv`` mesh axis. Because the space is dense and the
partition is even, "slicing" degenerates to integer math that XLA can fold
into the compiled program — no runtime key matching is needed.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True, order=True)
class KeyRange:
    """Half-open range [begin, end) over the dense key space."""

    begin: int
    end: int

    def __post_init__(self) -> None:
        if self.begin > self.end:
            raise ValueError(f"invalid range [{self.begin}, {self.end})")

    @property
    def size(self) -> int:
        return self.end - self.begin

    def contains(self, key: int) -> bool:
        return self.begin <= key < self.end

    def intersect(self, other: "KeyRange") -> "KeyRange":
        b, e = max(self.begin, other.begin), min(self.end, other.end)
        return KeyRange(b, max(b, e))

    def even_divide(self, n: int) -> list["KeyRange"]:
        """Split into n near-equal contiguous ranges (ref Range::EvenDivide)."""
        if n <= 0:
            raise ValueError("n must be positive")
        out = []
        for i in range(n):
            b = self.begin + (self.size * i) // n
            e = self.begin + (self.size * (i + 1)) // n
            out.append(KeyRange(b, e))
        return out

    def shard_of(self, key: int, n: int) -> int:
        """Index of the even_divide(n) shard containing ``key``."""
        if not self.contains(key):
            raise ValueError(f"key {key} outside {self}")
        off = key - self.begin
        # inverse of the even_divide boundary formula
        return min(n - 1, (off * n + n - 1) // self.size if self.size else 0)
