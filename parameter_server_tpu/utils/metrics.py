"""Progress reporting and metrics (reference analog: learner/sgd.h Progress
protos merged at the scheduler + glog step tables, util/resource_usage.h
tic/toc timers).

The reference's scheduler merges per-worker ``Progress`` protos (objective,
relative objv, AUC, nnz(w), examples/sec) every ``report_interval`` and
prints a table. Here ``ProgressReporter`` does the same for the SPMD pod:
workers contribute dicts, process 0 prints the table and appends JSONL.
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path
from typing import Any


class CounterSet:
    """Thread-safe named monotonic counters (ref: the Postoffice per-node
    counter tables). One process-global instance, ``wire_counters``, is the
    observability spine of the self-healing control plane: RpcClient bumps
    ``rpc_retries``/``rpc_reconnects`` on every mid-call failure it
    absorbs, RpcServer bumps ``rpc_dedup_hits`` when the reply cache
    suppresses a resent/duplicated non-idempotent command, and the chaos
    layer bumps ``fault_<action>`` per injected fault — so a recovery test
    can assert not just that a run survived but that the machinery it
    claims to test actually engaged."""

    def __init__(self) -> None:
        self._d: dict[str, int] = {}
        self._lock = threading.Lock()

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._d[name] = self._d.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._d.get(name, 0)

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._d)

    def reset(self) -> None:
        """Zero everything (tests only: production counters are cumulative
        for the life of the process, like the reference's)."""
        with self._lock:
            self._d.clear()


#: process-global wire/recovery counters (see CounterSet docstring)
wire_counters = CounterSet()


class Timer:
    """tic/toc accumulator (ref: util/resource_usage.h)."""

    def __init__(self) -> None:
        self._t0: float | None = None
        self.total = 0.0
        self.count = 0

    def tic(self) -> None:
        self._t0 = time.perf_counter()

    def toc(self) -> float:
        assert self._t0 is not None, "toc without tic"
        dt = time.perf_counter() - self._t0
        self.total += dt
        self.count += 1
        self._t0 = None
        return dt

    def __enter__(self) -> "Timer":
        self.tic()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.toc()


class ProgressReporter:
    """Merge progress dicts; print a step table; append JSONL.

    Columns follow the reference's printed progress (objv, relative objv,
    AUC, nnz(w), examples/sec) plus bytes moved by collectives — the
    reference's Postoffice per-filter byte counters become a statically
    computed collective-traffic estimate.
    """

    _COLS = ("sec", "examples", "objv", "rel_objv", "auc", "nnz_w", "ex_per_sec")

    def __init__(self, jsonl_path: str | Path | None = None, print_fn=print):
        self._path = Path(jsonl_path) if jsonl_path else None
        self._print = print_fn
        self._start = time.perf_counter()
        self._last_objv: float | None = None
        self._header_printed = False
        self.history: list[dict[str, Any]] = []

    def report(self, **fields: Any) -> dict[str, Any]:
        now = time.perf_counter() - self._start
        rec: dict[str, Any] = {"sec": round(now, 3), **fields}
        objv = fields.get("objv")
        if objv is not None and self._last_objv not in (None, 0.0):
            rec["rel_objv"] = (self._last_objv - objv) / abs(self._last_objv)
        if objv is not None:
            self._last_objv = float(objv)
        self.history.append(rec)
        if self._path is not None:
            with self._path.open("a") as f:
                f.write(json.dumps(rec) + "\n")
        self._print_row(rec)
        return rec

    def _print_row(self, rec: dict[str, Any]) -> None:
        if not self._header_printed:
            self._print("  ".join(f"{c:>12}" for c in self._COLS))
            self._header_printed = True
        cells = []
        for c in self._COLS:
            v = rec.get(c, "")
            if isinstance(v, float):
                cells.append(f"{v:>12.5g}")
            else:
                cells.append(f"{v!s:>12}")
        self._print("  ".join(cells))


def merge_progress(reports: list[dict[str, Any]]) -> dict[str, Any]:
    """Merge per-worker progress the way the reference scheduler does:
    sums for counters, example-weighted means for metrics."""
    if not reports:
        return {}
    out: dict[str, Any] = {}
    n = sum(r.get("examples", 0) for r in reports)
    out["examples"] = n
    for k in ("objv", "auc", "logloss"):
        pairs = [(r[k], r.get("examples", 0)) for r in reports if k in r]
        if pairs:
            if all(w > 0 for _, w in pairs):
                tot = sum(w for _, w in pairs)
                out[k] = sum(x * w for x, w in pairs) / tot
            else:  # any report without a count: fall back to unweighted mean
                out[k] = sum(x for x, _ in pairs) / len(pairs)
    for k in (
        "nnz_w",
        "ex_per_sec",
        "bytes_pushed",
        "bytes_pulled",
        "wire_bytes_out",
        "wire_bytes_in",
        "est_collective_bytes",
        # self-healing control plane (each worker reports its cumulative
        # wire_counters; the merge is the cluster total)
        "rpc_retries",
        "rpc_reconnects",
        "rpc_dedup_hits",
    ):
        vals = [r[k] for r in reports if k in r]
        if vals:
            out[k] = sum(vals)
    return out
