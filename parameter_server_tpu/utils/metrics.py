"""Progress reporting and metrics (reference analog: learner/sgd.h Progress
protos merged at the scheduler + glog step tables, util/resource_usage.h
tic/toc timers).

The reference's scheduler merges per-worker ``Progress`` protos (objective,
relative objv, AUC, nnz(w), examples/sec) every ``report_interval`` and
prints a table. Here ``ProgressReporter`` does the same for the SPMD pod:
workers contribute dicts, process 0 prints the table and appends JSONL.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from pathlib import Path
from typing import Any

import numpy as np


class CounterSet:
    """Thread-safe named monotonic counters (ref: the Postoffice per-node
    counter tables). One process-global instance, ``wire_counters``, is the
    observability spine of the self-healing control plane: RpcClient bumps
    ``rpc_retries``/``rpc_reconnects`` on every mid-call failure it
    absorbs, RpcServer bumps ``rpc_dedup_hits`` when the reply cache
    suppresses a resent/duplicated non-idempotent command, and the chaos
    layer bumps ``fault_<action>`` per injected fault — so a recovery test
    can assert not just that a run survived but that the machinery it
    claims to test actually engaged."""

    def __init__(self) -> None:
        self._d: dict[str, int] = {}
        # windowed high-watermarks: the same *_peak gauges, but reset at
        # every roll_peaks snapshot — so the telemetry plane reports
        # peak-since-last-snapshot and a one-time spike DECAYS out of
        # ``cli stats`` instead of latching forever (ISSUE 9 satellite)
        self._win: dict[str, int] = {}
        self._lock = threading.Lock()

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._d[name] = self._d.get(name, 0) + n

    def inc_many(self, items: dict[str, int]) -> None:
        """Several counters under ONE lock acquisition (hot-path callers
        like the header codec bump two per frame)."""
        with self._lock:
            d = self._d
            for name, n in items.items():
                d[name] = d.get(name, 0) + n

    def observe_max(self, name: str, v: int) -> None:
        """High-watermark counter (e.g. ``rpc_inflight_peak``: the deepest
        pipelined request window any connection actually reached).
        Tracked twice: cumulative (``get``/plain ``snapshot``) and per
        telemetry window (``snapshot(roll_peaks=True)``)."""
        with self._lock:
            if v > self._d.get(name, 0):
                self._d[name] = v
            if v > self._win.get(name, 0):
                self._win[name] = v

    def get(self, name: str) -> int:
        with self._lock:
            return self._d.get(name, 0)

    def snapshot(self, roll_peaks: bool = False) -> dict[str, int]:
        """Counter snapshot. ``roll_peaks=True`` (the telemetry/heartbeat
        path) reports each ``observe_max`` gauge's peak SINCE THE LAST
        ROLL and resets that window — so the cluster dashboard shows
        recent peaks, not peak-since-boot; ``get()`` and the default
        snapshot keep the cumulative value for tests and process-exit
        reporting."""
        with self._lock:
            out = dict(self._d)
            if roll_peaks:
                out.update(self._win)
                for k in self._win:
                    self._win[k] = 0
            return out

    def reset(self) -> None:
        """Zero everything (tests only: production counters are cumulative
        for the life of the process, like the reference's)."""
        with self._lock:
            self._d.clear()
            self._win.clear()


#: process-global wire/recovery counters (see CounterSet docstring)
wire_counters = CounterSet()


def race_track(obj, fields: tuple[str, ...], name: str = "") -> None:
    """Register one shared object's fields with the Eraser-style lockset
    race witness (analysis/racewitness.py) IF it is armed
    (``PS_RACE_WITNESS=1`` or an explicit ``install()``). Resolved
    through ``sys.modules`` so production code never imports the
    analysis package: disarmed cost is one dict lookup at CONSTRUCTION
    time and zero per attribute access. The owning constructors are the
    registration sites — an instance built before arming keeps raw
    attributes (its locks are raw too; observing it would report
    phantom races)."""
    rw = sys.modules.get("parameter_server_tpu.analysis.racewitness")
    if rw is not None and rw.installed():
        rw.track(obj, fields, name)


#: log2 latency buckets: bucket i covers [2^(i-1), 2^i) microseconds
#: (bucket 0 is < 1 us); 40 buckets reach ~9 days — nothing clips
_HIST_BUCKETS = 40


class Histogram:
    """Thread-safe log2-bucketed latency histogram (ref: the scheduler's
    per-link latency accounting the comm-optimization papers require).

    Observations are seconds; buckets are powers of two of microseconds,
    so the whole distribution is ~40 ints — cheap to snapshot into a
    heartbeat and exact to merge across nodes (bucket-wise sums).

    **Tail-trace exemplars** (ISSUE 15): an observation may carry an
    exemplar id (the trace id of the RPC it measures); the histogram
    retains the id of the max-latency observation of the current window
    (rolled with the peak-gauge discipline — ``snapshot(roll_exemplar=
    True)`` is the telemetry/heartbeat path, plain reads observe
    without consuming). The exemplar rides snapshots as ``ex`` and the
    OpenMetrics exposition as the standard exemplar syntax, linking a
    p99 blowup on a dashboard to the retained trace that caused it."""

    __slots__ = ("_counts", "_count", "_sum", "_ex", "_lock")

    def __init__(self) -> None:
        self._counts = [0] * _HIST_BUCKETS
        self._count = 0
        self._sum = 0.0
        self._ex: tuple[float, str, float] | None = None  # (v_s, tid, ts)
        self._lock = threading.Lock()

    def observe(self, seconds: float, exemplar: str | None = None) -> None:
        i = int(seconds * 1e6).bit_length()
        if i >= _HIST_BUCKETS:
            i = _HIST_BUCKETS - 1
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += seconds
            if exemplar is not None and (
                self._ex is None or seconds > self._ex[0]
            ):
                self._ex = (seconds, exemplar, time.time())

    def snapshot(self, roll_exemplar: bool = False) -> dict[str, Any]:
        """Wire-friendly form: sparse ``{bucket_index: count}`` (JSON
        string keys) plus count/sum — what heartbeats piggyback — and
        the window's max-latency exemplar (``ex``) when one was
        recorded. ``roll_exemplar=True`` resets the exemplar window
        (the telemetry plane's roll; observe-only readers like the
        blackbox flusher and ``/metrics`` scrapes must not consume)."""
        with self._lock:
            out: dict[str, Any] = {
                "count": self._count,
                "sum_s": self._sum,
                "buckets": {
                    str(i): c for i, c in enumerate(self._counts) if c
                },
            }
            if self._ex is not None:
                out["ex"] = {
                    "v": self._ex[0], "tid": self._ex[1], "ts": self._ex[2],
                }
                if roll_exemplar:
                    self._ex = None
            return out

    def percentile(self, p: float) -> float:
        return hist_percentile(self.snapshot(), p)


def hist_percentile(snap: dict[str, Any], p: float) -> float:
    """p-quantile (0..1) in SECONDS from a Histogram snapshot: the upper
    edge of the bucket holding the p-th observation (log2 resolution —
    good enough for p50/p99 dashboards, exact under merging)."""
    total = snap.get("count", 0)
    if not total:
        return 0.0
    target = max(1, int(p * total + 0.9999999))
    cum = 0
    for i in sorted(int(k) for k in snap.get("buckets", {})):
        cum += snap["buckets"][str(i)]
        if cum >= target:
            return (1 << i) / 1e6  # bucket i upper edge in us
    return (1 << (_HIST_BUCKETS - 1)) / 1e6


def merge_hist_snapshots(snaps: list[dict[str, Any]]) -> dict[str, Any]:
    """Bucket-wise sum of Histogram snapshots (the cluster-wide merge);
    exemplars merge as the max-latency one — the cluster's worst
    observation keeps its trace id through the merge."""
    out: dict[str, Any] = {"count": 0, "sum_s": 0.0, "buckets": {}}
    for s in snaps:
        out["count"] += s.get("count", 0)
        out["sum_s"] += s.get("sum_s", 0.0)
        for k, c in s.get("buckets", {}).items():
            out["buckets"][k] = out["buckets"].get(k, 0) + c
        ex = s.get("ex")
        if ex and ex.get("v", 0.0) > (out.get("ex") or {}).get("v", 0.0):
            out["ex"] = dict(ex)
    return out


class HistogramSet:
    """Named histograms (thread-safe, created on first observe). One
    process-global instance, ``latency_histograms``, holds per-command
    RPC latencies: ``client.<cmd>`` (client-observed, includes retries)
    and ``server.<cmd>`` (server dispatch/service time)."""

    def __init__(self) -> None:
        self._d: dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def observe(
        self, name: str, seconds: float, exemplar: str | None = None
    ) -> None:
        h = self._d.get(name)
        if h is None:
            with self._lock:
                h = self._d.setdefault(name, Histogram())
        h.observe(seconds, exemplar=exemplar)

    def get(self, name: str) -> Histogram | None:
        with self._lock:
            return self._d.get(name)

    def snapshot(
        self, roll_exemplars: bool = False
    ) -> dict[str, dict[str, Any]]:
        with self._lock:
            hists = dict(self._d)
        return {
            k: h.snapshot(roll_exemplar=roll_exemplars)
            for k, h in hists.items()
        }

    def reset(self) -> None:
        """Tests/benchmarks only (see CounterSet.reset)."""
        with self._lock:
            self._d.clear()


#: process-global per-command RPC latency histograms
latency_histograms = HistogramSet()


class SlowOps:
    """Bounded slowest-K RPCs per command with a per-call segment split
    (ISSUE 15's live leg of latency forensics).

    Fed by the RPC client's completion path: every reply now echoes the
    server's service time (``_svc_us``; batched pushes add apply-queue
    wait ``_apw_us`` and jitted-apply ``_apl_us``), so the client can
    split its observed wall time into **wire** (client-observed minus
    server-observed — queueing on the socket, the network, server recv
    buffering, any reply-lane withholding) vs **server** (dispatch)
    vs **apply_wait** / **apply**, with no span shipping. Records carry
    the trace id when tracing is armed, linking a live slow op to its
    retained tail trace. Entries expire after ``window_s`` so the view
    tracks *now*; the whole structure rides the heartbeat piggyback
    (``telemetry_snapshot()["slow"]``) the way hot stacks do."""

    def __init__(self, k: int = 8, window_s: float = 60.0):
        self.k = max(1, int(k))
        self.window_s = float(window_s)
        self._d: dict[str, list[dict[str, Any]]] = {}
        self._lock = threading.Lock()

    def observe(
        self,
        cmd: str,
        total_s: float,
        svc_us: float | None = None,
        apw_us: float | None = None,
        apl_us: float | None = None,
        tid: str | None = None,
    ) -> None:
        now = time.time()
        with self._lock:
            recs = self._d.get(cmd)
            if recs is None:
                recs = self._d[cmd] = []
            lo = now - self.window_s
            if recs:
                # prune unconditionally: recs is DURATION-sorted, so no
                # single position's timestamp proves the rest are live —
                # stale giants must not hold slots, evict live records
                # or fast-reject new ones against a dead floor (k <= 8,
                # the scan is trivial)
                recs[:] = [r for r in recs if r["ts"] >= lo]
            if len(recs) >= self.k and total_s * 1e3 <= recs[-1]["dur_ms"]:
                return  # fast reject: not in the window's slowest-K
            rec: dict[str, Any] = {
                "cmd": cmd,
                "dur_ms": round(total_s * 1e3, 3),
                "ts": now,
            }
            if tid is not None:
                rec["tid"] = tid
            if svc_us is not None:
                svc_ms = float(svc_us) / 1e3
                apw_ms = float(apw_us or 0) / 1e3
                apl_ms = float(apl_us or 0) / 1e3
                seg = {
                    "wire": round(max(total_s * 1e3 - svc_ms, 0.0), 3),
                    "server": round(max(svc_ms - apw_ms - apl_ms, 0.0), 3),
                }
                if apw_us is not None:
                    seg["apply_wait"] = round(apw_ms, 3)
                if apl_us is not None:
                    seg["apply"] = round(apl_ms, 3)
                rec["seg"] = seg
            recs.append(rec)
            recs.sort(key=lambda r: -r["dur_ms"])
            del recs[self.k:]

    def snapshot(self) -> dict[str, list[dict[str, Any]]]:
        """Per-cmd slowest-K records (duration-descending), window-
        expired; {} when nothing slow was seen."""
        now = time.time()
        lo = now - self.window_s
        with self._lock:
            out = {}
            for cmd, recs in self._d.items():
                live = [dict(r) for r in recs if r["ts"] >= lo]
                if live:
                    out[cmd] = live
            return out

    def reset(self) -> None:
        """Tests/benchmarks only (see CounterSet.reset)."""
        with self._lock:
            self._d.clear()


#: process-global slowest-RPC records (fed by RpcClient completions)
slow_ops = SlowOps()


def merge_slow_ops(
    blocks: list[dict[str, list[dict[str, Any]]]], k: int = 8
) -> dict[str, list[dict[str, Any]]]:
    """Cluster merge of SlowOps snapshots: per-cmd concatenation,
    duration-descending, trimmed to the slowest ``k``."""
    out: dict[str, list[dict[str, Any]]] = {}
    for b in blocks:
        for cmd, recs in (b or {}).items():
            out.setdefault(cmd, []).extend(recs)
    for cmd, recs in out.items():
        recs.sort(key=lambda r: -r.get("dur_ms", 0.0))
        del recs[k:]
    return out


def observe_scalar(name: str, value: float) -> None:
    """Dimensionless histogram observation (apply-batch sizes, queue
    depths) through the same log2-bucketed machinery as the latency
    histograms: the value is recorded as if it were that many
    microseconds, so ``hist_percentile(snap, p) * 1e6`` recovers the
    value percentile. Sharing ``latency_histograms`` means these ride
    the heartbeat/telemetry plane (and ``cli stats``) with zero extra
    plumbing; the ``.n`` suffix convention (``server.apply_batch.n``)
    marks a series as a count, not a latency."""
    latency_histograms.observe(name, value / 1e6)


#: range-series naming (ISSUE 17 freshness plane): every per-key-range
#: metric is an ORDINARY counter/histogram whose name carries a
#: ``range.<begin>-<end>.`` prefix. The encoding is the whole design:
#: the heartbeat piggyback, the coordinator's delta rings,
#: merge_telemetry, beat saturation and the SLO engine all treat the
#: series like any other, so the per-range matrix rides the existing
#: plumbing end to end; only render time (the OpenMetrics endpoint,
#: ``cli ranges``) parses the prefix back into a bounded label.
RANGE_PREFIX = "range."

#: the overflow bucket every cardinality guard folds excess ranges into
#: (a real range id is always ``<begin>-<end>``, so it can never collide)
RANGE_OTHER = "other"


def split_range_series(name: str) -> tuple[str, str] | None:
    """``range.<id>.<metric>`` -> ``(<id>, <metric>)``; None for any
    other series name (the id itself never contains a dot)."""
    if not name.startswith(RANGE_PREFIX):
        return None
    rest = name[len(RANGE_PREFIX):]
    rid, dot, metric = rest.partition(".")
    if not dot or not rid or not metric:
        return None
    return rid, metric


class RangeScope:
    """Booking facade for one key range's traffic matrix: push/pull
    counts, bytes, apply cost and realized data age, all landing in the
    shared ``wire_counters``/``latency_histograms`` under this range's
    name prefix (see RANGE_PREFIX). One instance per ShardServer (its
    owned range) and per serving handle (the range it proxies) — both
    sides contribute to the SAME series, which is exactly right: a
    cached client serve is a serve of that range's data, and
    merge_telemetry unions the contributions cluster-wide."""

    __slots__ = (
        "rid", "_c_pull", "_c_pull_bytes", "_c_push", "_c_push_bytes",
        "_h_apply", "_h_age",
    )

    def __init__(self, begin: int, end: int) -> None:
        self.rid = f"{int(begin)}-{int(end)}"
        p = RANGE_PREFIX + self.rid + "."
        self._c_pull = p + "pull"
        self._c_pull_bytes = p + "pull_bytes"
        self._c_push = p + "push"
        self._c_push_bytes = p + "push_bytes"
        self._h_apply = p + "apply"
        self._h_age = p + "age"

    def pull(self, nbytes: int = 0) -> None:
        wire_counters.inc(self._c_pull)
        if nbytes:
            wire_counters.inc(self._c_pull_bytes, int(nbytes))

    def push(self, n: int = 1, nbytes: int = 0) -> None:
        if n:
            wire_counters.inc(self._c_push, int(n))
        if nbytes:
            wire_counters.inc(self._c_push_bytes, int(nbytes))

    def apply(self, seconds: float) -> None:
        latency_histograms.observe(self._h_apply, seconds)

    def age(self, age_s: float) -> None:
        latency_histograms.observe(self._h_age, max(age_s, 0.0))


def known_ranges(telemetry: dict[str, Any]) -> list[tuple[int, int]]:
    """The distinct key ranges present in a telemetry block's
    ``range.<begin>-<end>.*`` series names, sorted by begin. The rid
    string IS the range boundary, so the shard layout is recoverable
    from the metrics alone — no side channel to the coordinator's
    config, and a merged cluster block yields the cluster layout."""
    rids: set[str] = set()
    for blk in ("counters", "hists"):
        for name in (telemetry.get(blk) or {}):
            parsed = split_range_series(name)
            if parsed and parsed[0] != RANGE_OTHER:
                rids.add(parsed[0])
    out: list[tuple[int, int]] = []
    for rid in rids:
        b, dash, e = rid.partition("-")
        if dash and b.isdigit() and e.isdigit():
            out.append((int(b), int(e)))
    return sorted(out)


def owning_range(
    key: int, ranges: list[tuple[int, int]]
) -> tuple[int, tuple[int, int]] | None:
    """``(server rank, (begin, end))`` owning global ``key`` — ranks
    follow sorted-range order, the ``even_divide`` assignment every
    backend uses; None when no known range covers the key."""
    for i, (b, e) in enumerate(ranges):
        if b <= key < e:
            return i, (b, e)
    return None


class Timer:
    """tic/toc accumulator (ref: util/resource_usage.h).

    Thread-safe: the live ``t0`` is thread-local (the checkpoint thread
    and serve threads tic/toc concurrently without racing each other's
    start marks) and the totals are lock-protected."""

    def __init__(self) -> None:
        self._local = threading.local()
        self._lock = threading.Lock()
        self.total = 0.0
        self.count = 0

    def tic(self) -> None:
        self._local.t0 = time.perf_counter()

    def toc(self) -> float:
        t0 = getattr(self._local, "t0", None)
        assert t0 is not None, "toc without tic"
        dt = time.perf_counter() - t0
        self._local.t0 = None
        with self._lock:
            self.total += dt
            self.count += 1
        return dt

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return {"total_s": self.total, "count": self.count}

    def __enter__(self) -> "Timer":
        self.tic()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.toc()


class TimerRegistry:
    """Process-global named timers (ref: resource_usage.h's named tic/toc
    tables): ``timers.timer("trainer.dispatch")`` returns one shared
    Timer per name, and ``snapshot()`` rides the telemetry plane."""

    def __init__(self) -> None:
        self._d: dict[str, Timer] = {}
        self._lock = threading.Lock()

    def timer(self, name: str) -> Timer:
        t = self._d.get(name)
        if t is None:
            with self._lock:
                t = self._d.setdefault(name, Timer())
        return t

    def snapshot(self) -> dict[str, dict[str, float]]:
        with self._lock:
            ts = dict(self._d)
        return {k: t.snapshot() for k, t in ts.items()}

    def reset(self) -> None:
        """Tests/benchmarks only."""
        with self._lock:
            self._d.clear()


#: process-global named-timer registry (included in telemetry snapshots)
timers = TimerRegistry()


#: count-min hash seeds (splitmix64 salts; must agree across every node
#: for the sketch tables to be mergeable by elementwise sum)
_HEAT_SEEDS = (0x9E37, 0x85EB, 0xC2B2, 0x27D4)


class KeyHeatSketch:
    """Per-key access heat: a small count-min sketch over the GLOBAL key
    ids touched by pulls and pushes, plus an exact hot-candidate list
    (ISSUE 9 — the feed hot-key replication (#1) and tiered-store
    promotion (#4) will consume).

    Mergeable like the PR-2 histograms: same seeds + geometry on every
    node, so tables sum elementwise and estimates stay one-sided
    (count-min never under-counts). ``snapshot()`` is heartbeat-sized:
    the sparse table rows ride along until they saturate
    (``_SNAP_MAX_NNZ`` nonzeros), after which only the bounded
    hot-candidate list travels — a terabyte-scale run degrades to
    heavy-hitters-only, never to an unbounded beat payload."""

    _SNAP_MAX_NNZ = 4096

    def __init__(
        self, width: int = 1024, depth: int = 2,
        hot_min: int = 8, hot_cap: int = 64,
    ):
        if depth > len(_HEAT_SEEDS):
            raise ValueError(f"depth <= {len(_HEAT_SEEDS)}")
        self.width = int(width)
        self.depth = int(depth)
        self.hot_min = int(hot_min)
        self.hot_cap = int(hot_cap)
        self._t = np.zeros((self.depth, self.width), np.int64)
        self._n = 0
        self._hot: dict[int, int] = {}  # candidate key -> last estimate
        self._lock = threading.Lock()
        # lockset race witness (PS_RACE_WITNESS=1): the sketch is fed
        # from server conn threads and drained by heartbeat snapshots —
        # every _t/_n/_hot access must hold _lock
        race_track(self, ("_t", "_n", "_hot"), "KeyHeatSketch")

    def _rows(self, keys: np.ndarray) -> np.ndarray:
        from parameter_server_tpu.utils.hashing import splitmix64

        k = np.asarray(keys).astype(np.uint64, copy=False)
        out = np.empty((self.depth, len(k)), np.int64)
        for d in range(self.depth):
            with np.errstate(over="ignore"):
                out[d] = (
                    splitmix64(k ^ np.uint64(_HEAT_SEEDS[d]))
                    % np.uint64(self.width)
                ).astype(np.int64)
        return out

    def add(self, keys: np.ndarray) -> None:
        """Count one access of each key (vectorized; GLOBAL key ids —
        callers offset range-relative keys by their range begin)."""
        keys = np.asarray(keys)
        if len(keys) == 0:
            return
        idx = self._rows(keys)
        # the sketch is process-global and every serving/decode thread
        # feeds it, so the scatter (ufunc.at is slow) happens OUTSIDE
        # the lock as a per-depth bincount; the critical section is one
        # dense (depth, width) add + the gather
        contrib = np.stack([
            np.bincount(idx[d], minlength=self.width)
            for d in range(self.depth)
        ])
        with self._lock:
            self._t += contrib
            self._n += len(keys)
            est = self._t[np.arange(self.depth)[:, None], idx].min(axis=0)
            hot = est >= self.hot_min
            if hot.any():
                for k, c in zip(keys[hot].tolist(), est[hot].tolist()):
                    self._hot[int(k)] = int(c)
                if len(self._hot) > 2 * self.hot_cap:
                    top = sorted(
                        self._hot.items(), key=lambda kv: -kv[1]
                    )[: self.hot_cap]
                    self._hot = dict(top)

    def count(self, keys: np.ndarray) -> np.ndarray:
        """Estimated access counts (never under-estimates)."""
        keys = np.asarray(keys)
        if len(keys) == 0:
            return np.zeros(0, np.int64)
        idx = self._rows(keys)
        with self._lock:
            return self._t[np.arange(self.depth)[:, None], idx].min(axis=0)

    def snapshot(self) -> dict[str, Any]:
        """Heartbeat-piggyback form ({} when nothing was counted): JSON
        ints only, sparse rows while under the nnz budget."""
        with self._lock:
            if self._n == 0:
                return {}
            out: dict[str, Any] = {
                "w": self.width, "d": self.depth, "n": int(self._n),
                "hot": {str(k): int(c) for k, c in self._hot.items()},
            }
            nnz = int(np.count_nonzero(self._t))
            if nnz <= self._SNAP_MAX_NNZ:
                out["rows"] = [
                    {
                        str(i): int(c)
                        for i, c in zip(
                            np.nonzero(self._t[d])[0].tolist(),
                            self._t[d][np.nonzero(self._t[d])].tolist(),
                        )
                    }
                    for d in range(self.depth)
                ]
            else:
                out["saturated"] = True
            return out

    def reset(self) -> None:
        """Tests/benchmarks only (see CounterSet.reset)."""
        with self._lock:
            self._t[:] = 0
            self._n = 0
            self._hot.clear()


#: process-global per-key heat (shard servers add touched pull/push keys)
key_heat = KeyHeatSketch()


def merge_heat_snapshots(snaps: list[dict[str, Any]]) -> dict[str, Any]:
    """Cluster merge of KeyHeatSketch snapshots: tables sum elementwise
    (same geometry/seeds everywhere), candidate lists sum per key.
    Geometry mismatches and saturated tables degrade to candidates-only."""
    snaps = [s for s in snaps if s]
    if not snaps:
        return {}
    out: dict[str, Any] = {
        "w": snaps[0].get("w"), "d": snaps[0].get("d"),
        "n": sum(s.get("n", 0) for s in snaps),
    }
    hot: dict[str, int] = {}
    for s in snaps:
        for k, c in s.get("hot", {}).items():
            hot[k] = hot.get(k, 0) + int(c)
    out["hot"] = hot
    rows: list[dict[str, int]] | None = None
    for s in snaps:
        sr = s.get("rows")
        if sr is None or (s.get("w"), s.get("d")) != (out["w"], out["d"]):
            rows = None
            out["saturated"] = True
            break
        if rows is None:
            rows = [dict(r) for r in sr]
        else:
            for d, r in enumerate(sr):
                acc = rows[d]
                for i, c in r.items():
                    acc[i] = acc.get(i, 0) + int(c)
    if rows is not None:
        out["rows"] = rows
    return out


def heat_top(snap: dict[str, Any], k: int = 10) -> list[tuple[int, int]]:
    """Top-k (key, estimated count) from a (possibly merged) heat
    snapshot. With the sparse table present, candidate keys re-query the
    merged table (consistent cluster-wide estimates); a saturated
    snapshot falls back to the summed candidate counts."""
    if not snap:
        return []
    cand = [int(key) for key in snap.get("hot", {})]
    if not cand:
        return []
    rows = snap.get("rows")
    if rows is not None:
        sk = KeyHeatSketch(width=int(snap["w"]), depth=int(snap["d"]))
        for d, r in enumerate(rows):
            for i, c in r.items():
                sk._t[d, int(i)] = int(c)
        counts = sk.count(np.asarray(cand, np.uint64))
        pairs = [(key, int(c)) for key, c in zip(cand, counts.tolist())]
    else:
        pairs = [(int(key), int(c)) for key, c in snap["hot"].items()]
    pairs.sort(key=lambda kv: (-kv[1], kv[0]))
    return pairs[:k]


def _profiler_top() -> list[dict[str, Any]] | None:
    """The continuous profiler's top-N hot stacks IF it is armed
    (utils/profiler.py) — resolved through ``sys.modules`` like
    ``race_track``, so an unprofiled process never imports the profiler
    and the disarmed cost is one dict lookup per snapshot."""
    pm = sys.modules.get("parameter_server_tpu.utils.profiler")
    if pm is not None and pm.enabled():
        return pm.top_stacks()
    return None


def telemetry_snapshot(roll_peaks: bool = True) -> dict[str, Any]:
    """This process's full telemetry state — counters, per-command
    latency histograms, named timers, per-key heat. Small (sparse
    dicts), so nodes piggyback it on every heartbeat and the coordinator
    merges the cluster view without a second collection path. Peak
    gauges roll here: each snapshot reports peak-since-last-snapshot
    (see ``CounterSet.snapshot``). ``roll_peaks=False`` observes without
    consuming the window — for readers that are not the telemetry plane
    (the blackbox flusher dumps every second; if it rolled, heartbeats
    and ``cli stats`` would always see ~0 peaks on an armed node)."""
    out = {
        "counters": wire_counters.snapshot(roll_peaks=roll_peaks),
        # exemplars roll with the peak windows: the telemetry plane
        # consumes each window's max-latency trace id exactly once
        "hists": latency_histograms.snapshot(roll_exemplars=roll_peaks),
        "timers": timers.snapshot(),
    }
    heat = key_heat.snapshot()
    if heat:
        out["key_heat"] = heat
    prof = _profiler_top()
    if prof:
        out["prof"] = prof
    slow = slow_ops.snapshot()
    if slow:
        out["slow"] = slow
    return out


def merge_telemetry(snaps: list[dict[str, Any]]) -> dict[str, Any]:
    """Cluster merge of telemetry snapshots: counters and timers sum,
    histograms merge bucket-wise (exact — no quantile averaging).
    High-watermark gauges (``*_peak``, fed by ``observe_max``) merge as a
    max — summing per-node peaks would report a depth nothing reached."""
    counters: dict[str, int] = {}
    hists: dict[str, list[dict]] = {}
    tmr: dict[str, dict[str, float]] = {}
    heat: list[dict[str, Any]] = []
    prof: dict[str, int] = {}
    slow: list[dict[str, Any]] = []
    for s in snaps:
        for k, v in s.get("counters", {}).items():
            if k.endswith("_peak"):
                counters[k] = max(counters.get(k, 0), v)
            else:
                counters[k] = counters.get(k, 0) + v
        for k, v in s.get("hists", {}).items():
            hists.setdefault(k, []).append(v)
        for k, v in s.get("timers", {}).items():
            t = tmr.setdefault(k, {"total_s": 0.0, "count": 0})
            t["total_s"] += v.get("total_s", 0.0)
            t["count"] += v.get("count", 0)
        if s.get("key_heat"):
            heat.append(s["key_heat"])
        if s.get("slow"):
            slow.append(s["slow"])
        for p in s.get("prof") or ():
            stack = str(p.get("s", ""))
            prof[stack] = prof.get(stack, 0) + int(p.get("n", 0))
    out = {
        "counters": counters,
        "hists": {k: merge_hist_snapshots(v) for k, v in hists.items()},
        "timers": tmr,
    }
    if heat:
        out["key_heat"] = merge_heat_snapshots(heat)
    if slow:
        out["slow"] = merge_slow_ops(slow)
    if prof:
        # cluster-wide hot stacks: sum per folded stack, keep a bounded
        # heaviest-first list (each node's block is already top-N)
        ranked = sorted(prof.items(), key=lambda kv: -kv[1])[:20]
        out["prof"] = [{"s": s, "n": n} for s, n in ranked]
    return out


def format_latency_table(hists: dict[str, dict[str, Any]]) -> str:
    """Per-command latency table (count / mean / p50 / p99 in ms) from a
    ``hists`` snapshot — the core of the ``cli stats`` dashboard."""
    lines = [f"{'command':<28} {'count':>9} {'mean_ms':>9} {'p50_ms':>9} {'p99_ms':>9}"]
    for name in sorted(hists):
        s = hists[name]
        n = s.get("count", 0)
        mean = (s.get("sum_s", 0.0) / n * 1e3) if n else 0.0
        lines.append(
            f"{name:<28} {n:>9} {mean:>9.3f} "
            f"{hist_percentile(s, 0.5) * 1e3:>9.3f} "
            f"{hist_percentile(s, 0.99) * 1e3:>9.3f}"
        )
    return "\n".join(lines)


def format_cluster_stats(rep: dict[str, Any]) -> str:
    """The cluster telemetry dump (ref: the reference scheduler's live
    dashboard table): one row per node (liveness stats + headline
    counters), then the merged per-command latency table."""
    lines = [
        f"{'node':>5} {'role':<10} {'rank':>5} {'rss_mb':>8} "
        f"{'wire_out':>12} {'wire_in':>12} {'saved':>10} "
        f"{'retries':>8} {'dedup':>6}"
    ]
    for nid in sorted(rep.get("nodes", {}), key=lambda x: int(x)):
        n = rep["nodes"][nid]
        stats = n.get("stats", {})
        ctr = (n.get("telemetry") or {}).get("counters", {})
        lines.append(
            f"{nid:>5} {str(n.get('role', '?')):<10} "
            f"{str(n.get('rank', '')):>5} "
            f"{stats.get('max_rss_mb', float('nan')):>8.1f} "
            f"{ctr.get('wire_bytes_out', 0):>12} "
            f"{ctr.get('wire_bytes_in', 0):>12} "
            f"{ctr.get('wire_bytes_saved', 0):>10} "
            f"{ctr.get('rpc_retries', 0):>8} "
            f"{ctr.get('rpc_dedup_hits', 0):>6}"
        )
    merged = rep.get("merged", {})
    lines.append("")
    lines.append("cluster counters (merged):")
    ctr = merged.get("counters", {})
    for k in sorted(ctr):
        lines.append(f"  {k:<28} {ctr[k]}")
    heat = merged.get("key_heat")
    if heat:
        lines.append("")
        lines.append(
            f"hot keys (count-min heat, {heat.get('n', 0)} accesses "
            "counted, top 10):"
        )
        # freshness plane (ISSUE 17): place each hot key on the shard
        # map — the owning range/rank comes straight from the merged
        # range.<begin>-<end>.* series names, no extra plumbing
        ranges = known_ranges(merged)
        for key, c in heat_top(heat, 10):
            own = owning_range(int(key), ranges)
            loc = (
                f"  [range {own[1][0]}-{own[1][1]} @ server {own[0]}]"
                if own else ""
            )
            lines.append(f"  key {key:<24} ~{c}{loc}")
    lines.append("")
    lines.append("per-command latency (merged across nodes):")
    lines.append(format_latency_table(merged.get("hists", {})))
    return "\n".join(lines)


class ProgressReporter:
    """Merge progress dicts; print a step table; append JSONL.

    Columns follow the reference's printed progress (objv, relative objv,
    AUC, nnz(w), examples/sec) plus bytes moved by collectives — the
    reference's Postoffice per-filter byte counters become a statically
    computed collective-traffic estimate.
    """

    _COLS = (
        "sec", "examples", "objv", "rel_objv", "auc", "nnz_w", "ex_per_sec",
        # recovery columns (merge_progress sums these cluster-wide; a table
        # that never showed them hid the self-healing plane's activity)
        "rpc_retries", "rpc_reconnects", "rpc_dedup_hits",
    )
    #: re-print the header periodically so long runs stay readable when
    #: the top scrolled away (ref: glog's repeating table headers)
    _HEADER_EVERY = 25

    def __init__(self, jsonl_path: str | Path | None = None, print_fn=print):
        self._path = Path(jsonl_path) if jsonl_path else None
        self._print = print_fn
        self._start = time.perf_counter()
        self._last_objv: float | None = None
        self._rows_since_header = self._HEADER_EVERY  # first row prints it
        self.history: list[dict[str, Any]] = []

    def report(self, **fields: Any) -> dict[str, Any]:
        now = time.perf_counter() - self._start
        rec: dict[str, Any] = {"sec": round(now, 3), **fields}
        objv = fields.get("objv")
        if objv is not None and self._last_objv not in (None, 0.0):
            rec["rel_objv"] = (self._last_objv - objv) / abs(self._last_objv)
        if objv is not None:
            self._last_objv = float(objv)
        self.history.append(rec)
        if self._path is not None:
            with self._path.open("a") as f:
                f.write(json.dumps(rec) + "\n")
        self._print_row(rec)
        return rec

    def _print_row(self, rec: dict[str, Any]) -> None:
        if self._rows_since_header >= self._HEADER_EVERY:
            self._print("  ".join(f"{c:>12}" for c in self._COLS))
            self._rows_since_header = 0
        self._rows_since_header += 1
        cells = []
        for c in self._COLS:
            v = rec.get(c, "")
            if isinstance(v, float):
                cells.append(f"{v:>12.5g}")
            else:
                cells.append(f"{v!s:>12}")
        self._print("  ".join(cells))


def merge_progress(reports: list[dict[str, Any]]) -> dict[str, Any]:
    """Merge per-worker progress the way the reference scheduler does:
    sums for counters, example-weighted means for metrics."""
    if not reports:
        return {}
    out: dict[str, Any] = {}
    n = sum(r.get("examples", 0) for r in reports)
    out["examples"] = n
    for k in ("objv", "auc", "logloss"):
        pairs = [(r[k], r.get("examples", 0)) for r in reports if k in r]
        if pairs:
            if all(w > 0 for _, w in pairs):
                tot = sum(w for _, w in pairs)
                out[k] = sum(x * w for x, w in pairs) / tot
            else:  # any report without a count: fall back to unweighted mean
                out[k] = sum(x for x, _ in pairs) / len(pairs)
    for k in (
        "nnz_w",
        "ex_per_sec",
        "bytes_pushed",
        "bytes_pulled",
        "wire_bytes_out",
        "wire_bytes_in",
        "wire_bytes_saved",
        "wire_comp_skipped",
        "est_collective_bytes",
        # self-healing control plane (each worker reports its cumulative
        # wire_counters; the merge is the cluster total)
        "rpc_retries",
        "rpc_reconnects",
        "rpc_dedup_hits",
    ):
        vals = [r[k] for r in reports if k in r]
        if vals:
            out[k] = sum(vals)
    return out
