"""Host-side utilities (reference analog: src/util/)."""

from parameter_server_tpu.utils.hashing import hash_keys, splitmix64  # noqa: F401
from parameter_server_tpu.utils.keyrange import KeyRange  # noqa: F401
