"""Sharded checkpoint / resume and text model dumps.

Reference analog: each server dumps its own key range at SaveModel (text
``key\\tweight`` lines or recordio) and reloads it on recovery — i.e.
checkpointing is naturally sharded by key range. Here:

- ``save_checkpoint`` writes one ``shard-K-of-N.npz`` per kv shard plus a
  JSON manifest (step counters, SSP clock, data cursor, config echo);
  single-host runs write N=1 but the format is shard-native.
- ``dump_weights_text`` / ``load_weights_text`` is the reference's text
  model dump (nonzero weights only — FTRL lazy sparsity keeps this small),
  consumed by the model_evaluation app.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import numpy as np

MANIFEST = "manifest.json"


def _flatten(state: dict[str, Any], prefix: str = "") -> dict[str, np.ndarray]:
    out = {}
    for k, v in state.items():
        name = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, name + "/"))
        else:
            out[name] = np.asarray(v)
    return out


def _unflatten(flat: dict[str, np.ndarray]) -> dict[str, Any]:
    out: dict[str, Any] = {}
    for k, v in flat.items():
        parts = k.split("/")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out


def save_checkpoint(
    ckpt_dir: str | Path,
    state: dict[str, Any],
    meta: dict[str, Any] | None = None,
    shard_id: int = 0,
    num_shards: int = 1,
) -> Path:
    """Write this shard's slice of ``state`` (a pytree of arrays) + manifest.

    In multi-host runs each host calls this with its shard_id and its local
    slice; the manifest is written by shard 0."""
    d = Path(ckpt_dir)
    d.mkdir(parents=True, exist_ok=True)
    flat = _flatten(state)
    np.savez(d / f"shard-{shard_id}-of-{num_shards}.npz", **flat)
    if shard_id == 0:
        manifest = {
            "num_shards": num_shards,
            "arrays": {k: {"shape": list(v.shape), "dtype": str(v.dtype)} for k, v in flat.items()},
            "meta": meta or {},
        }
        (d / MANIFEST).write_text(json.dumps(manifest, indent=1))
    return d


def load_checkpoint(
    ckpt_dir: str | Path, shard_id: int | None = None
) -> tuple[dict[str, Any], dict[str, Any]]:
    """Load (state, meta). shard_id=None concatenates all shards on axis 0
    (the key axis — shards are contiguous ranges); shard_id=k loads one."""
    d = Path(ckpt_dir)
    manifest = json.loads((d / MANIFEST).read_text())
    n = manifest["num_shards"]
    if shard_id is not None:
        flat = dict(np.load(d / f"shard-{shard_id}-of-{n}.npz"))
        return _unflatten(flat), manifest["meta"]
    shards = [dict(np.load(d / f"shard-{i}-of-{n}.npz")) for i in range(n)]
    flat = {
        k: (np.concatenate([s[k] for s in shards], axis=0) if n > 1 else shards[0][k])
        for k in shards[0]
    }
    return _unflatten(flat), manifest["meta"]


def dump_weights_text(weights: np.ndarray, path: str | Path, tol: float = 0.0) -> int:
    """Reference-style model dump: one ``key\\tweight`` line per nonzero
    weight (vdim==1). Returns the number of lines written."""
    w = np.asarray(weights).reshape(-1)
    nz = np.nonzero(np.abs(w) > tol)[0]
    with open(path, "w") as f:
        for k in nz:
            f.write(f"{int(k)}\t{w[k]:.9g}\n")
    return len(nz)


def load_weights_text(path: str | Path, num_keys: int) -> np.ndarray:
    """Inverse of dump_weights_text -> dense (num_keys,) float32."""
    w = np.zeros(num_keys, dtype=np.float32)
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            k, _, v = line.partition("\t")
            ki = int(k)
            if not 0 <= ki < num_keys:
                raise ValueError(f"key {ki} outside [0, {num_keys}) in {path}")
            w[ki] = float(v)
    return w
