"""Feature-id hashing into a static dense key space.

The reference keeps raw 64-bit feature keys end-to-end and range-partitions
the (sparse) key space across servers (ref: src/util/range.h EvenDivide,
src/app/linear_method/localizer.h remaps to dense local ids per block).

On TPU we need *static shapes*: raw ids are hashed once, at ingest, into a
dense space ``[0, num_keys)`` sized to pod HBM. The hash is splitmix64's
finalizer — invertible (bijective on uint64), cheap, and implementable
identically in vectorized numpy (here) and C++ (native/ parser extension),
so the two ingest paths agree bit-for-bit.

Slots (the reference's feature groups, ref: src/data/proto/example.proto
Slot ids) are mixed into the hash as a salt so distinct slots land in
decorrelated regions of the same space.
"""

from __future__ import annotations

import numpy as np

_C1 = np.uint64(0x9E3779B97F4A7C15)
_C2 = np.uint64(0xBF58476D1CE4E5B9)
_C3 = np.uint64(0x94D049BB133111EB)


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer. Bijective on uint64."""
    z = x.astype(np.uint64, copy=True)
    with np.errstate(over="ignore"):
        z += _C1
        z = (z ^ (z >> np.uint64(30))) * _C2
        z = (z ^ (z >> np.uint64(27))) * _C3
        z = z ^ (z >> np.uint64(31))
    return z


def hash_keys(
    raw_keys: np.ndarray, num_keys: int, slot_ids: np.ndarray | int = 0
) -> np.ndarray:
    """Hash raw 64-bit feature ids (optionally salted by slot) into [0, num_keys).

    Index 0 of every table is reserved as the padding row (gradients routed
    there are discarded), so hashed ids land in [1, num_keys).
    """
    if num_keys < 2:
        raise ValueError(f"num_keys must be >= 2 (pad row + data), got {num_keys}")
    raw = np.asarray(raw_keys, dtype=np.uint64)
    salt = np.asarray(slot_ids, dtype=np.uint64)
    with np.errstate(over="ignore"):
        mixed = raw ^ (splitmix64(salt + _C1))
    h = splitmix64(mixed)
    usable = np.uint64(num_keys - 1)
    return (h % usable + np.uint64(1)).astype(np.int64)


PAD_KEY = 0  # reserved padding row in every table
