"""Live cluster time series: bounded rings of telemetry DELTAS.

Reference analog: none — the reference's scheduler dashboard printed the
*latest* heartbeat and nothing else, so "is the shed rate rising?" was
unanswerable while the cluster ran. This module is the missing axis:
every process's cumulative telemetry (``metrics.telemetry_snapshot()``
— monotonic counters + log2-bucketed latency histograms) is observed
into a :class:`TimeSeriesRing`, which stores the timestamped DELTA since
the previous observation. Deltas make the history composable:

- a counter delta over ``dt`` seconds is an exact windowed **rate**;
- a histogram delta is an exact bucket-wise difference, so a window's
  **p50/p99** comes from summing the window's delta buckets and reading
  the percentile — no quantile averaging, the same discipline as the
  PR-2 cluster merge;
- ``*_peak`` gauges (already rolled per heartbeat window upstream) ride
  each entry as-is and merge as a max.

Fed from two sides (ISSUE 13): **client-side**, every node rolls its own
ring from the same ``telemetry_snapshot()`` call its heartbeat
piggybacks (``local_roll``); **cluster-side**, ``HeartbeatMonitor``
retains each node's beat stream in a per-node ring instead of
overwriting the last beat — the feed for the coordinator ``telemetry``
command's windowed view, ``cli top`` and the ``[slo]`` burn-rate engine
(utils/slo.py).

The **OpenMetrics endpoint** (``start_metrics_server``) serves this
process's cumulative telemetry at ``/metrics`` (strict OpenMetrics text:
counters with ``_total``, log2 histograms with cumulative ``le``
buckets, ``# EOF`` terminator) plus a ``/healthz`` liveness probe, over
a stdlib ``ThreadingHTTPServer`` — an external Prometheus can scrape
any node with zero dependencies.

The **heartbeat payload guard** rides here too: ``beat_telemetry()`` is
what ``_Beats`` actually piggybacks — the full snapshot saturates to
summaries once it outgrows the per-beat budget (the
``KeyHeatSketch._SNAP_MAX_NNZ`` discipline), so a long run's beat stays
bounded no matter how many histogram series or profiler stacks the
process accumulates.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Callable

from parameter_server_tpu.utils import flightrec
from parameter_server_tpu.utils.metrics import (
    _HIST_BUCKETS,
    RANGE_OTHER,
    RANGE_PREFIX,
    hist_percentile,
    merge_hist_snapshots,
    split_range_series,
    telemetry_snapshot,
    wire_counters,
)

#: clip bucket for exemplar placement (metrics.Histogram's top bucket)
_HIST_TOP_BUCKET = _HIST_BUCKETS - 1

METRICS_PORT_ENV = "PS_METRICS_PORT"

#: heartbeat payload guard (ISSUE 13 satellite): a beat's telemetry
#: block keeps at most this many histogram series — beyond it, the
#: largest-count series survive and the rest collapse into one
#: ``{count, sum_s}``-only summary entry, flagged ``hists_saturated``
BEAT_MAX_HISTS = 64
#: ... and at most this many piggybacked profiler stacks, each folded
#: string truncated (utils/profiler.py already bounds depth; this bound
#: holds even against a misconfigured profiler)
BEAT_MAX_PROF = 8
BEAT_MAX_STACK_CHARS = 1024
#: freshness plane (ISSUE 17): at most this many DISTINCT key ranges may
#: ride one beat's ``range.<id>.*`` matrix — a resharded or synthetic
#: run with thousands of ranges collapses its cold tail into one
#: ``range.other.*`` aggregate, so range cardinality can never blow up
#: a heartbeat (the same discipline BEAT_MAX_HISTS applies to series
#: count, applied one level up to the range axis specifically: without
#: this, 10k ranges x 6 series each would saturate the hist guard and
#: crowd every NON-range series out of the beat)
BEAT_MAX_RANGES = 32


def _counter_deltas(
    cur: dict[str, int], prev: dict[str, int]
) -> dict[str, int]:
    out: dict[str, int] = {}
    for k, v in cur.items():
        if k.endswith("_peak"):
            # windowed gauge (rolled upstream per snapshot): the entry
            # value IS the window's peak, not a cumulative difference
            if v:
                out[k] = v
            continue
        d = v - prev.get(k, 0)
        if d < 0:
            d = v  # process restart reset the counter: re-baseline
        if d:
            out[k] = d
    return out


def _hist_delta(
    cur: dict[str, Any], prev: dict[str, Any] | None
) -> dict[str, Any] | None:
    if prev is None or cur.get("count", 0) < prev.get("count", 0):
        # first sight, or the count went BACKWARDS (restart — or a
        # series that fell out of a saturated beat payload and came
        # back): baseline only, book NO delta. Booking the cumulative
        # snapshot here would re-count the series' whole history as one
        # window delta and inflate every rate/percentile the SLO engine
        # reads; losing one interval is the safe failure mode.
        return None
    c = cur.get("count", 0) - prev.get("count", 0)
    if c <= 0:
        return None
    pb = prev.get("buckets", {})
    buckets = {}
    for k, v in cur.get("buckets", {}).items():
        d = v - pb.get(k, 0)
        if d > 0:
            buckets[k] = d
    out = {
        "count": c,
        "sum_s": max(cur.get("sum_s", 0.0) - prev.get("sum_s", 0.0), 0.0),
        "buckets": buckets,
    }
    if "ex" in cur:
        # the exemplar is already windowed upstream (rolled per
        # telemetry snapshot): ride the delta as-is
        out["ex"] = cur["ex"]
    return out


def series_scale(name: str) -> float:
    """Display scale for a histogram series' percentile: latency series
    read in milliseconds; ``.n``-suffixed count-valued series
    (``observe_scalar``'s as-if-microseconds encoding) read back as raw
    values (``hist_percentile * 1e6``)."""
    return 1e6 if name.endswith(".n") else 1e3


#: committed series renamed for unit-suffix hygiene (pslint v3's
#: ``units`` checker: a duration-valued series name must carry its
#: unit): old name -> canonical. Rule strings and dashboard lookups
#: canonicalize through here, so persisted ``[slo] rules`` entries and
#: beats from pre-rename nodes in a mixed-version cluster keep working.
SERIES_ALIASES: dict[str, str] = {
    "serve.age": "serve.age_s",
}
#: canonical -> legacy, for read-side fallbacks against old beats
LEGACY_SERIES: dict[str, str] = {v: k for k, v in SERIES_ALIASES.items()}


def canonical_series(name: str) -> str:
    """The canonical (unit-suffixed) name for a telemetry series."""
    return SERIES_ALIASES.get(name, name)


class TimeSeriesRing:
    """Bounded ring of timestamped telemetry deltas (thread-safe).

    ``observe(cumulative_snapshot, ts)`` appends the delta vs the
    previous observation; windowed reads (``window``/``rate``/
    ``percentile``/``summary``) merge the entries younger than
    ``window_s``. The same class serves both feeds: a node observing its
    own rolls and the coordinator observing each node's beat stream."""

    def __init__(self, capacity: int = 360):
        self.capacity = max(int(capacity), 2)
        self._buf: deque[dict[str, Any]] = deque(maxlen=self.capacity)
        self._prev: dict[str, Any] | None = None
        self._prev_ts: float | None = None
        self._lock = threading.Lock()

    def observe(
        self, snap: dict[str, Any], ts: float | None = None
    ) -> dict[str, Any] | None:
        """Record the delta between ``snap`` (a cumulative telemetry
        snapshot) and the previous observation. The first observation
        only baselines (returns None) — a delta needs two points."""
        if ts is None:
            ts = time.time()
        with self._lock:
            prev, prev_ts = self._prev, self._prev_ts
            if prev_ts is not None and ts <= prev_ts:
                # out-of-order feeder race: discard WITHOUT touching the
                # baseline — regressing _prev to the older snapshot
                # would make the next delta double-count this interval
                return None
            self._prev, self._prev_ts = snap, ts
            if prev is None or prev_ts is None:
                return None
            hists: dict[str, Any] = {}
            for name, cur in (snap.get("hists") or {}).items():
                d = _hist_delta(cur, (prev.get("hists") or {}).get(name))
                if d is not None:
                    hists[name] = d
            entry = {
                "ts": ts,
                "dt_s": ts - prev_ts,
                "counters": _counter_deltas(
                    snap.get("counters") or {}, prev.get("counters") or {}
                ),
                "hists": hists,
            }
            self._buf.append(entry)
            return entry

    def entries(
        self, window_s: float | None = None, now: float | None = None
    ) -> list[dict[str, Any]]:
        with self._lock:
            out = list(self._buf)
        if window_s is None:
            return out
        if now is None:
            now = time.time()
        lo = now - window_s
        # strict cut: an entry stamped exactly at the window edge covers
        # the second BEFORE the window, so it stays out — a "4 s window"
        # then merges exactly 4 s of delta coverage, not 5
        return [e for e in out if e["ts"] > lo]

    def window(
        self, window_s: float, now: float | None = None
    ) -> dict[str, Any]:
        """One merged delta over the window: summed counters (peaks as
        max), bucket-wise merged histogram deltas, total covered dt."""
        counters: dict[str, int] = {}
        hists: dict[str, list[dict]] = {}
        dt = 0.0
        n = 0
        for e in self.entries(window_s, now):
            dt += e["dt_s"]
            n += 1
            for k, v in e["counters"].items():
                if k.endswith("_peak"):
                    counters[k] = max(counters.get(k, 0), v)
                else:
                    counters[k] = counters.get(k, 0) + v
            for k, v in e["hists"].items():
                hists.setdefault(k, []).append(v)
        return {
            "dt_s": dt,
            "samples": n,
            "counters": counters,
            "hists": {k: merge_hist_snapshots(v) for k, v in hists.items()},
        }

    def rate(
        self, counter: str, window_s: float, now: float | None = None
    ) -> float:
        w = self.window(window_s, now)
        return w["counters"].get(counter, 0) / w["dt_s"] if w["dt_s"] else 0.0

    def percentile(
        self, hist: str, p: float, window_s: float,
        now: float | None = None,
    ) -> float:
        """Windowed percentile in SECONDS (callers scale for display —
        see ``series_scale``); 0.0 when the window has no observations."""
        w = self.window(window_s, now)
        snap = w["hists"].get(hist)
        return hist_percentile(snap, p) if snap else 0.0

    def summary(
        self, window_s: float, now: float | None = None
    ) -> dict[str, Any]:
        """The wire/dashboard form: windowed counter rates (per second)
        and per-series p50/p99 in display units (ms for latency series,
        raw values for ``.n`` count series)."""
        w = self.window(window_s, now)
        dt = w["dt_s"]
        rates = {
            k: round(v / dt, 3)
            for k, v in sorted(w["counters"].items())
            if not k.endswith("_peak")
        } if dt else {}
        p50: dict[str, float] = {}
        p99: dict[str, float] = {}
        hist_rates: dict[str, float] = {}
        for name, snap in sorted(w["hists"].items()):
            if snap.get("buckets"):
                # bucketless deltas (the beat guard's "_saturated"
                # count/sum summary) have no percentile — emitting one
                # would report the top bucket edge (~6 days) as a p99
                sc = series_scale(name)
                p50[name] = round(hist_percentile(snap, 0.5) * sc, 3)
                p99[name] = round(hist_percentile(snap, 0.99) * sc, 3)
            if dt:
                # observations per second: command histograms double as
                # the dashboard's push/s / pull/s throughput columns
                hist_rates[name] = round(snap.get("count", 0) / dt, 3)
        return {
            "window_s": window_s,
            "dt_s": round(dt, 3),
            "samples": w["samples"],
            "rates": rates,
            "hist_rates": hist_rates,
            "peaks": {
                k: v for k, v in sorted(w["counters"].items())
                if k.endswith("_peak")
            },
            "p50": p50,
            "p99": p99,
        }


# -- the node-local ring + roll ---------------------------------------------

_local = TimeSeriesRing()


def local_ring() -> TimeSeriesRing:
    """This process's own ring (fed by ``local_roll``; served windowed
    by the metrics endpoint and piggybacked summaries)."""
    return _local


def reset_local_ring(capacity: int = 360) -> TimeSeriesRing:
    """Swap in a fresh ring (process start / tests)."""
    global _local
    _local = TimeSeriesRing(capacity)
    return _local


def local_roll(snap: dict[str, Any] | None = None) -> dict[str, Any]:
    """Observe one cumulative snapshot into the local ring (the
    heartbeat path passes the snapshot it is about to piggyback so one
    beat costs one snapshot). Returns the snapshot."""
    if snap is None:
        snap = telemetry_snapshot()
    _local.observe(snap)
    wire_counters.inc("ts_rolls")
    flightrec.record("ts.roll", n=len(snap.get("counters") or {}))
    return snap


class Roller:
    """Background roll cadence for processes with no heartbeat (the
    train path, benches): one daemon thread calling ``local_roll`` every
    ``interval_s``."""

    def __init__(self, interval_s: float = 5.0):
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="ps-ts-roller"
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            local_roll()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


# -- heartbeat payload guard ------------------------------------------------


def _saturate_ranges(
    counters: dict[str, int], hists: dict[str, Any]
) -> tuple[dict[str, int], dict[str, Any], int, int]:
    """Bound the distinct key ranges in one telemetry block to
    ``BEAT_MAX_RANGES``: the highest-traffic ranges keep their own
    ``range.<id>.*`` series, the tail folds into summed
    ``range.other.*`` counters and bucket-merged histograms (percentiles
    over the folded tail stay exact — the PR-2 merge discipline).
    Returns ``(counters, hists, n_ranges, n_folded)``."""
    traffic: dict[str, int] = {}
    for name, v in counters.items():
        parsed = split_range_series(name)
        if parsed and parsed[0] != RANGE_OTHER:
            traffic[parsed[0]] = traffic.get(parsed[0], 0) + int(v)
    for name, s in hists.items():
        parsed = split_range_series(name)
        if parsed and parsed[0] != RANGE_OTHER:
            traffic[parsed[0]] = traffic.get(parsed[0], 0) + int(
                s.get("count", 0)
            )
    n = len(traffic)
    if n <= BEAT_MAX_RANGES:
        return counters, hists, n, 0
    keep = set(
        sorted(traffic, key=lambda r: (-traffic[r], r))[:BEAT_MAX_RANGES]
    )
    c_out: dict[str, int] = {}
    for name, v in counters.items():
        parsed = split_range_series(name)
        if parsed is None or parsed[0] in keep:
            c_out[name] = v
        else:
            oname = RANGE_PREFIX + RANGE_OTHER + "." + parsed[1]
            c_out[oname] = c_out.get(oname, 0) + int(v)
    h_out: dict[str, Any] = {}
    folded: dict[str, list] = {}
    for name, s in hists.items():
        parsed = split_range_series(name)
        if parsed is None or parsed[0] in keep:
            h_out[name] = s
        else:
            folded.setdefault(parsed[1], []).append(s)
    for metric, snaps in folded.items():
        oname = RANGE_PREFIX + RANGE_OTHER + "." + metric
        if oname in h_out:  # an upstream fold already contributed
            snaps = snaps + [h_out[oname]]
        h_out[oname] = merge_hist_snapshots(snaps)
    return c_out, h_out, n, n - BEAT_MAX_RANGES


def beat_telemetry(snap: dict[str, Any] | None = None) -> dict[str, Any]:
    """The bounded beat payload: the cumulative snapshot with its
    range matrix, histogram and profiler blocks saturated to summaries
    past the per-beat budget. Also rolls the local ring (one snapshot
    serves the beat, the ring and the guard)."""
    snap = local_roll(snap)
    out = dict(snap)
    counters, hists, n_ranges, folded = _saturate_ranges(
        dict(snap.get("counters") or {}), dict(snap.get("hists") or {})
    )
    out["counters"] = counters
    if n_ranges:
        if folded:
            out["ranges_saturated"] = folded
            # always-rendered OpenMetrics saturation counter: a scraper
            # can tell "tail folded into range=other" from "few ranges"
            wire_counters.inc("range_label_saturated", folded)
        flightrec.record("range.roll", ranges=n_ranges, folded=folded)
    if len(hists) > BEAT_MAX_HISTS:
        # keep the heaviest series whole; the tail collapses into ONE
        # count/sum-only summary so the beat can never grow unboundedly
        # with series cardinality (the KeyHeatSketch saturation move)
        ranked = sorted(
            hists.items(), key=lambda kv: -kv[1].get("count", 0)
        )
        kept = dict(ranked[:BEAT_MAX_HISTS])
        dropped = ranked[BEAT_MAX_HISTS:]
        kept["_saturated"] = {
            "count": sum(s.get("count", 0) for _, s in dropped),
            "sum_s": sum(s.get("sum_s", 0.0) for _, s in dropped),
            "buckets": {},
        }
        hists = kept
        out["hists_saturated"] = len(dropped)
    out["hists"] = hists
    prof = snap.get("prof")
    if prof:
        out["prof"] = [
            {
                "s": str(p.get("s", ""))[:BEAT_MAX_STACK_CHARS],
                "n": int(p.get("n", 0)),
            }
            for p in prof[:BEAT_MAX_PROF]
        ]
    return out


# -- OpenMetrics endpoint ---------------------------------------------------

_NAME_OK = "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:"


def _metric_name(raw: str) -> str:
    cleaned = "".join(c if c in _NAME_OK else "_" for c in raw)
    if not cleaned or cleaned[0].isdigit():
        cleaned = "_" + cleaned
    return "ps_" + cleaned


def _fmt(v: float) -> str:
    return repr(round(float(v), 9)) if isinstance(v, float) else str(v)


def build_info(proc: str = "") -> dict[str, str]:
    """The ``ps_build_info`` labels: package version plus the role/rank
    parsed from the process name (``worker-3`` -> worker/3 — the naming
    convention every armed plane shares)."""
    import parameter_server_tpu

    role, _, rank = (proc or "").rpartition("-")
    if not role or not rank.isdigit():
        role, rank = proc, ""
    return {
        "version": getattr(parameter_server_tpu, "__version__", "0"),
        "role": role,
        "rank": rank,
    }


#: hard cap on distinct ``range="<id>"`` label values per scrape — a
#: Prometheus time-series database pays per label combination forever,
#: so the exposition folds the cold tail into ``range="other"`` rather
#: than letting reshards mint unbounded series (the classic cardinality
#: explosion). Tighter than BEAT_MAX_RANGES: a scrape is an external,
#: durable sink; a beat is internal and windowed.
OM_MAX_RANGE_LABELS = 16


def _label_set(*parts: str) -> str:
    """``{a="1",b="2"}`` from the non-empty parts ('' when none)."""
    body = ",".join(p for p in parts if p)
    return "{" + body + "}" if body else ""


def _fold_render_ranges(
    counters: dict[str, Any], hists: dict[str, Any]
) -> tuple[dict[str, dict], dict[str, dict], int]:
    """Pull every ``range.<id>.<metric>`` series OUT of the two blocks
    (mutating them) into per-metric ``{rid: value}`` / ``{rid: hist}``
    maps for labeled rendering, keeping only the ``OM_MAX_RANGE_LABELS``
    highest-traffic ids distinct — the rest (including any upstream
    ``other`` fold riding the snapshot) merge into ``rid="other"``.
    Returns ``(range_counters, range_hists, n_folded)``."""
    traffic: dict[str, int] = {}
    rc: dict[str, dict] = {}
    rh: dict[str, dict] = {}
    for name in list(counters):
        parsed = split_range_series(name)
        if parsed is None:
            continue
        rid, metric = parsed
        v = counters.pop(name)
        rc.setdefault(metric, {})[rid] = v
        if rid != RANGE_OTHER:
            traffic[rid] = traffic.get(rid, 0) + int(v)
    for name in list(hists):
        parsed = split_range_series(name)
        if parsed is None:
            continue
        rid, metric = parsed
        s = hists.pop(name)
        rh.setdefault(metric, {})[rid] = s
        if rid != RANGE_OTHER:
            traffic[rid] = traffic.get(rid, 0) + int(s.get("count", 0))
    if len(traffic) <= OM_MAX_RANGE_LABELS:
        return rc, rh, 0
    keep = set(
        sorted(traffic, key=lambda r: (-traffic[r], r))[:OM_MAX_RANGE_LABELS]
    )
    for metric, by_rid in rc.items():
        out: dict[str, Any] = {}
        for rid, v in by_rid.items():
            if rid in keep:
                out[rid] = v
            else:
                out[RANGE_OTHER] = out.get(RANGE_OTHER, 0) + int(v)
        rc[metric] = out
    for metric, by_rid in rh.items():
        out = {}
        fold: list[dict] = []
        for rid, s in by_rid.items():
            if rid in keep:
                out[rid] = s
            else:
                fold.append(s)
        if fold:
            out[RANGE_OTHER] = merge_hist_snapshots(fold)
        rh[metric] = out
    return rc, rh, len(traffic) - OM_MAX_RANGE_LABELS


def _render_hist(
    lines: list[str], m: str, s: dict[str, Any], base: str,
    count_valued: bool,
) -> None:
    """One histogram exposition (cumulative ``le`` buckets, sum, count)
    under label body ``base`` (e.g. ``proc="w-0",range="0-64"``)."""
    buckets = {int(k): int(v) for k, v in s.get("buckets", {}).items()}
    # tail-trace exemplar (ISSUE 15): the window's max-latency
    # observation carries its trace id — rendered with the
    # OpenMetrics exemplar syntax on the bucket containing it, so a
    # dashboard's p99 spike links straight to the retained trace
    ex = s.get("ex") or {}
    ex_sfx = ""
    ex_bucket = -1
    if ex.get("tid") and not count_valued:
        v = float(ex.get("v", 0.0))
        ex_bucket = min(int(v * 1e6).bit_length(), _HIST_TOP_BUCKET)
        ex_ts = ex.get("ts")
        ex_sfx = (
            f' # {{trace_id="{ex["tid"]}"}} {_fmt(v)}'
            + (f" {_fmt(float(ex_ts))}" if ex_ts else "")
        )
    cum = 0
    for i in sorted(buckets):
        cum += buckets[i]
        edge = float(1 << i) if count_valued else (1 << i) / 1e6
        lab = _label_set(base, f'le="{_fmt(edge)}"')
        sfx = ex_sfx if i == ex_bucket else ""
        if sfx:
            ex_sfx = ""  # attach exactly once
        lines.append(f"{m}_bucket{lab} {cum}{sfx}")
    inf_lab = _label_set(base, 'le="+Inf"')
    # an exemplar whose bucket is absent (merged/rolled snapshots)
    # attaches to +Inf — an exemplar must never be silently lost
    lines.append(f"{m}_bucket{inf_lab} {s.get('count', 0)}{ex_sfx}")
    total = s.get("sum_s", 0.0)
    if count_valued:
        total *= 1e6  # decode the as-if-microseconds value encoding
    blab = _label_set(base)
    lines.append(f"{m}_sum{blab} {_fmt(float(total))}")
    lines.append(f"{m}_count{blab} {s.get('count', 0)}")


def render_openmetrics(
    snap: dict[str, Any], proc: str = ""
) -> str:
    """Strict OpenMetrics text exposition of one cumulative telemetry
    snapshot: counters (``_total``), ``*_peak`` gauges, histograms with
    cumulative ``le`` buckets at the log2 microsecond edges (exposed in
    seconds; ``.n`` count series in raw values), timers as two counters,
    ``# EOF`` terminator.

    The freshness plane's ``range.<id>.<metric>`` series render as
    LABELED families instead of one metric name per range —
    ``ps_range_pull_total{range="0-64"}``,
    ``ps_range_age_seconds_bucket{range="0-64",le=...}`` — capped at
    ``OM_MAX_RANGE_LABELS`` distinct ids (tail folds to
    ``range="other"``) so a reshard can never mint unbounded label
    cardinality into a scraper's TSDB.

    Three series are emitted UNCONDITIONALLY (the tier-1 format
    validator requires them): ``ps_build_info`` (the Prometheus
    info-metric idiom — constant 1 with version/role/rank labels, what
    dashboards join on), ``ps_audit_violations_total`` (ISSUE 14) and
    ``ps_range_label_saturated_total`` (ISSUE 17) — a clean cluster
    scrapes explicit 0s, so "nothing fired/folded" and "plane absent"
    are different observations."""
    plabel = f'proc="{proc}"' if proc else ""
    label = _label_set(plabel)
    lines: list[str] = []
    info = build_info(proc)
    info_labels = ",".join(
        f'{k}="{v}"' for k, v in sorted(info.items())
    )
    if proc:
        info_labels = f'proc="{proc}",' + info_labels
    lines.append("# TYPE ps_build_info gauge")
    lines.append(f"ps_build_info{{{info_labels}}} 1")
    counters = dict(snap.get("counters") or {})
    # always-present audit verdict counter (0 until a violation fires)
    counters.setdefault("audit_violations", 0)
    # ... and the range-label saturation counter (0 until a fold)
    counters.setdefault("range_label_saturated", 0)
    hists = dict(snap.get("hists") or {})
    range_c, range_h, _folded = _fold_render_ranges(counters, hists)
    for name in sorted(counters):
        v = counters[name]
        m = _metric_name(name)
        if name.endswith("_peak"):
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m}{label} {_fmt(v)}")
        else:
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m}_total{label} {_fmt(v)}")
    for metric in sorted(range_c):
        m = _metric_name("range_" + metric)
        lines.append(f"# TYPE {m} counter")
        for rid in sorted(range_c[metric]):
            lab = _label_set(plabel, f'range="{rid}"')
            lines.append(f"{m}_total{lab} {_fmt(range_c[metric][rid])}")
    for name in sorted(hists):
        s = hists[name]
        count_valued = name.endswith(".n")
        m = _metric_name(name if count_valued else name + "_seconds")
        lines.append(f"# TYPE {m} histogram")
        _render_hist(lines, m, s, plabel, count_valued)
    for metric in sorted(range_h):
        count_valued = metric.endswith(".n")
        m = _metric_name(
            "range_" + (metric if count_valued else metric + "_seconds")
        )
        lines.append(f"# TYPE {m} histogram")
        for rid in sorted(range_h[metric]):
            base = ",".join(
                p for p in (plabel, f'range="{rid}"') if p
            )
            _render_hist(
                lines, m, range_h[metric][rid], base, count_valued
            )
    for name in sorted(snap.get("timers") or {}):
        t = snap["timers"][name]
        m = _metric_name("timer_" + name)
        lines.append(f"# TYPE {m}_seconds counter")
        lines.append(
            f"{m}_seconds_total{label} {_fmt(float(t.get('total_s', 0.0)))}"
        )
        lines.append(f"# TYPE {m}_calls counter")
        lines.append(f"{m}_calls_total{label} {int(t.get('count', 0))}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class MetricsServer:
    """Stdlib HTTP scrape endpoint: ``/metrics`` (OpenMetrics text of
    this process's cumulative telemetry — Prometheus derives its own
    rates) + ``/healthz`` (JSON liveness INCLUDING this node's own
    windowed view: the local ring's rates/p99 summary, so a human or a
    load balancer can read "how is this node doing right now" without
    the coordinator). ``port=0`` binds an ephemeral port (tests);
    ``.port`` is the bound port either way.

    Port-collision fallback (ISSUE 14 satellite): a requested port
    already in use — a stale process, two clusters sharing one base
    port, a host service squatting on the offset — retries the next
    per-role offsets (``port + 1``, ``port + 2``, ... up to
    ``fallback_attempts``) instead of killing the node at arm time;
    telemetry must degrade to a different port, never take the data
    plane down. The chosen port is logged and served in ``/healthz``
    (``port`` + ``requested_port``), so a scraper that found nothing
    at the configured offset can still discover where the node went."""

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        process_name: str = "",
        snapshot_fn: Callable[[], dict[str, Any]] | None = None,
        health_fn: Callable[[], dict[str, Any]] | None = None,
        window_s: float = 60.0,
        fallback_attempts: int = 8,
    ):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.process_name = process_name
        self.requested_port = port
        # observe-only snapshots: a scrape must never consume the
        # heartbeat plane's rolled peak windows
        snap_fn = snapshot_fn or (
            lambda: telemetry_snapshot(roll_peaks=False)
        )
        # default health: liveness + the node's own windowed summary
        # over the configured [timeseries] window (the local ring is
        # fed by beat_telemetry / a Roller; _local resolves at call
        # time so a later reset_local_ring is picked up). The bound +
        # requested ports ride every health doc — the port-collision
        # fallback's discovery contract.
        hf = health_fn or (
            lambda: {"ok": True, "window": _local.summary(window_s)}
        )
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 — stdlib handler API
                try:
                    if self.path.split("?")[0] == "/metrics":
                        wire_counters.inc("ts_scrapes")
                        body = render_openmetrics(
                            snap_fn(), outer.process_name
                        ).encode()
                        ctype = (
                            "application/openmetrics-text; "
                            "version=1.0.0; charset=utf-8"
                        )
                    elif self.path.split("?")[0] == "/healthz":
                        doc = {
                            "proc": outer.process_name,
                            "time": time.time(),
                            "port": outer.port,
                            "requested_port": outer.requested_port,
                            **hf(),
                        }
                        body = (json.dumps(doc) + "\n").encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except BrokenPipeError:  # scraper hung up mid-reply
                    pass

            def log_message(self, *a: Any) -> None:  # stay silent
                pass

        # bind, walking past EADDRINUSE up to fallback_attempts per-role
        # offsets (ephemeral port 0 never collides: one bind, no walk)
        import errno

        attempts = max(int(fallback_attempts), 1) if port else 1
        httpd = None
        for i in range(attempts):
            try:
                httpd = ThreadingHTTPServer((host, port + i), Handler)
                break
            except OSError as e:
                if e.errno != errno.EADDRINUSE or i == attempts - 1:
                    raise
        self._httpd = httpd
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self.address = f"{host}:{self.port}"
        if port and self.port != port:
            print(
                f"[metrics] {process_name or 'node'}: port {port} in "
                f"use, bound {self.port} instead (fallback offset "
                f"+{self.port - port})",
                flush=True,
            )
        self._closed = False
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.2},
            daemon=True,
            name="ps-metrics",
        )
        self._thread.start()

    def close(self) -> None:
        if self._closed:  # idempotent: the train path's finally may
            return        # race an explicit close in tests
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


def start_metrics_server(
    port: int,
    process_name: str = "",
    snapshot_fn: Callable[[], dict[str, Any]] | None = None,
    health_fn: Callable[[], dict[str, Any]] | None = None,
    window_s: float = 60.0,
    host: str = "127.0.0.1",
) -> MetricsServer:
    """Bind and serve the OpenMetrics endpoint (see MetricsServer).
    The loopback default serves same-host scrapers; pass
    ``[timeseries] metrics_host = "0.0.0.0"`` for an off-host
    Prometheus."""
    return MetricsServer(
        port=port, host=host, process_name=process_name,
        snapshot_fn=snapshot_fn, health_fn=health_fn, window_s=window_s,
    )
