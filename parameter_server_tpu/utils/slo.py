"""SLO burn-rate engine + the live ``cli top`` dashboard renderer.

Reference analog: none — the reference's operator watched glog scroll.
This is the alerting half of the live operations plane (ISSUE 13):
declarative ``[slo]`` rules (utils/config.py SloConfig documents the
grammar) are evaluated as **multi-window burn rates** over each node's
time-series ring (utils/timeseries.py) at the coordinator:

- a rule's *bad fraction* over a window is the dt-weighted fraction of
  ring entries violating the threshold (rate rules compare each entry's
  counter delta / dt; percentile rules compare each entry's histogram
  delta's p50/p99);
- the *burn rate* is ``bad_fraction / (1 - target)`` — how many times
  faster than budget the error budget is burning (the SRE-workbook
  multi-window alert, scaled to a cluster that measures in heartbeats);
- an alert **fires once per episode**: the rising edge requires the
  burn to exceed the rule's threshold in BOTH the short window (it is
  happening now) and the long window (it is sustained, not a blip);
  the episode stays active while EITHER window still burns, and only a
  full recovery re-arms it. Rising edges record a ``slo.alert``
  flight-recorder event (+ the ``slo_alerts`` counter), so every alert
  lands in the black box and ``cli postmortem`` renders it.

Per-node **health** is the fraction of data-bearing rules not burning
(scored 0-100); a rule whose series has no data in the window neither
burns nor counts — ``replication_lag_s`` stays declared-but-dormant
until direction #1 emits it.

``format_top`` renders the auto-refreshing ``cli top`` frame from the
coordinator ``telemetry`` reply: per-node windowed rates + p99s, the
health column, hot keys (the PR-9 heat sketch) and the active alerts.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any

from parameter_server_tpu.utils import flightrec
from parameter_server_tpu.utils.metrics import (
    heat_top,
    hist_percentile,
    owning_range,
    split_range_series,
    wire_counters,
)
from parameter_server_tpu.utils.timeseries import (
    LEGACY_SERIES,
    TimeSeriesRing,
    canonical_series,
    series_scale,
)


@dataclass
class SloRule:
    """One parsed rule (grammar: utils/config.py SloConfig)."""

    name: str
    kind: str  # rate | p50 | p99
    series: str
    threshold: float
    target: float = 0.99
    burn: float = 10.0

    @property
    def budget(self) -> float:
        return max(1.0 - self.target, 1e-9)


_KINDS = ("rate", "p50", "p99")


def parse_rule(spec: str) -> SloRule:
    """``<name> <kind>:<series> <= <threshold> [target f] [burn x]``."""
    toks = spec.split()
    if len(toks) < 4 or toks[2] != "<=":
        raise ValueError(
            f"bad [slo] rule {spec!r}: expected "
            "'<name> <kind>:<series> <= <threshold> [target f] [burn x]'"
        )
    kind, _, series = toks[1].partition(":")
    if kind not in _KINDS or not series:
        raise ValueError(
            f"bad [slo] rule {spec!r}: kind must be one of {_KINDS} "
            "with a ':<series>' suffix"
        )
    rule = SloRule(
        # persisted rule strings may predate a series' unit-suffix
        # rename (serve.age -> serve.age_s): normalize at parse time
        name=toks[0], kind=kind, series=canonical_series(series),
        threshold=float(toks[3]),
    )
    rest = toks[4:]
    if len(rest) % 2:
        raise ValueError(f"bad [slo] rule {spec!r}: dangling option token")
    for k, v in zip(rest[::2], rest[1::2]):
        if k == "target":
            rule.target = float(v)
        elif k == "burn":
            rule.burn = float(v)
        else:
            raise ValueError(f"bad [slo] rule {spec!r}: unknown option {k!r}")
    return rule


def parse_rules(specs: list[str]) -> list[SloRule]:
    return [parse_rule(s) for s in specs]


@dataclass
class _Episode:
    since: float
    burn_short: float = 0.0
    burn_long: float = 0.0


@dataclass
class SloEngine:
    """Stateful multi-window evaluator (one per coordinator)."""

    rules: list[SloRule]
    short_window_s: float = 60.0
    long_window_s: float = 300.0
    _active: dict[tuple[str, str], _Episode] = field(default_factory=dict)
    episodes: int = 0  # rising edges fired this life
    # the recovery sweep and telemetry handlers evaluate concurrently;
    # episode check-then-fire must be atomic or one storm double-fires
    # (lambda, not the bare constructor: resolve threading.Lock at
    # instance-creation time so the runtime lock witness sees it)
    _lock: threading.Lock = field(default_factory=lambda: threading.Lock())

    def _bad_fraction(
        self, ring: TimeSeriesRing, rule: SloRule, window_s: float,
        now: float,
    ) -> float | None:
        """dt-weighted violating fraction over the window; None when the
        window holds no data for the rule's series (no data != bad —
        a dormant series must never page)."""
        total = bad = 0.0
        saw_data = False
        for e in ring.entries(window_s, now):
            dt = e["dt_s"]
            total += dt
            if rule.kind == "rate":
                v = e["counters"].get(rule.series, 0) / dt
                saw_data = True  # a counter absent from a delta is 0/s
            else:
                snap = e["hists"].get(rule.series)
                if snap is None:
                    # mixed-version cluster: an older node's beats still
                    # carry the pre-rename series name
                    legacy = LEGACY_SERIES.get(rule.series)
                    if legacy is not None:
                        snap = e["hists"].get(legacy)
                if not snap or not snap.get("buckets"):
                    # no observations this entry (or a bucketless
                    # saturation summary — no percentile): no verdict
                    continue
                saw_data = True
                p = 0.5 if rule.kind == "p50" else 0.99
                v = hist_percentile(snap, p) * series_scale(rule.series)
            if v > rule.threshold:
                bad += dt
        if not saw_data or total <= 0:
            return None
        return bad / total

    def evaluate(
        self,
        rings: dict[Any, TimeSeriesRing],
        now: float | None = None,
    ) -> dict[str, Any]:
        """One evaluation pass over every node's ring: returns
        ``{"alerts": [...], "health": {node: {...}}, "rules": [...]}``
        and fires/clears episodes as a side effect."""
        if now is None:
            now = time.time()
        with self._lock:
            return self._evaluate_locked(rings, now)

    def _evaluate_locked(
        self, rings: dict[Any, TimeSeriesRing], now: float
    ) -> dict[str, Any]:
        alerts: list[dict[str, Any]] = []
        health: dict[str, dict[str, Any]] = {}
        seen_keys: set[tuple[str, str]] = set()
        for node, ring in rings.items():
            nk = str(node)
            burning: list[str] = []
            evaluable = 0
            for rule in self.rules:
                fs = self._bad_fraction(ring, rule, self.short_window_s, now)
                fl = self._bad_fraction(ring, rule, self.long_window_s, now)
                if fs is None and fl is None:
                    # dormant series (e.g. replication_lag_s) — but a
                    # data GAP during an active episode must not end it:
                    # clearing here would make one sustained incident
                    # with a beat pause fire a second "rising edge" when
                    # data resumes. The episode survives (still alerted,
                    # last known burns) until real data recovers it.
                    ep = self._active.get((nk, rule.name))
                    if ep is not None:
                        seen_keys.add((nk, rule.name))
                        evaluable += 1  # still counts against health
                        burning.append(rule.name)
                        alerts.append({
                            "node": nk,
                            "rule": rule.name,
                            "burn_short": round(ep.burn_short, 1),
                            "burn_long": round(ep.burn_long, 1),
                            "since": round(ep.since, 3),
                            "stale": True,  # no fresh data this pass
                        })
                    continue
                evaluable += 1
                burn_s = (fs or 0.0) / rule.budget
                burn_l = (fl or 0.0) / rule.budget
                key = (nk, rule.name)
                seen_keys.add(key)
                ep = self._active.get(key)
                rising = burn_s >= rule.burn and burn_l >= rule.burn
                staying = burn_s >= rule.burn or burn_l >= rule.burn
                if ep is None and rising:
                    ep = self._active[key] = _Episode(since=now)
                    self.episodes += 1
                    wire_counters.inc("slo_alerts")
                    flightrec.record(
                        "slo.alert", rule=rule.name, node=nk,
                        burn_short=round(burn_s, 1),
                        burn_long=round(burn_l, 1),
                    )
                elif ep is not None and not staying:
                    # full recovery on both windows: the episode ends and
                    # the alert re-arms (fire-once-per-episode hysteresis)
                    del self._active[key]
                    ep = None
                if ep is not None:
                    ep.burn_short, ep.burn_long = burn_s, burn_l
                    burning.append(rule.name)
                    alerts.append({
                        "node": nk,
                        "rule": rule.name,
                        "burn_short": round(burn_s, 1),
                        "burn_long": round(burn_l, 1),
                        "since": round(ep.since, 3),
                    })
            score = (
                round(100.0 * (1.0 - len(burning) / evaluable))
                if evaluable else 100
            )
            health[nk] = {
                "score": score,
                "burning": burning,
                "rules_evaluated": evaluable,
            }
        # a node whose ring vanished (forgotten/dead) ends its episodes
        for key in [k for k in self._active if k not in seen_keys]:
            del self._active[key]
        return {
            "alerts": alerts,
            "health": health,
            "rules": [r.name for r in self.rules],
        }


# -- the `cli top` frame ----------------------------------------------------


def _first(d: dict[str, float], *names: str) -> float:
    for n in names:
        if n in d:
            return d[n]
    return 0.0


def format_violation(
    v: dict[str, Any], exclude: tuple[str, ...] = ("kind", "monitor"),
) -> str:
    """One audit violation as ``[kind] k=v ...`` — the ONE renderer the
    `cli audit` panel, its follow loop and `cli top` all share, so a
    new violation field shows up on every surface at once."""
    kv = " ".join(
        f"{k}={v[k]}" for k in sorted(v) if k not in exclude
    )
    return f"  [{v.get('kind')}] {kv}"


def format_top(rep: dict[str, Any], window_s: float) -> str:
    """Render one dashboard frame from a coordinator ``telemetry`` reply
    carrying ``series`` (per-node windowed summaries), ``slo`` and the
    ``audit`` plane's verdict."""
    series: dict[str, Any] = rep.get("series") or {}
    slo: dict[str, Any] = rep.get("slo") or {}
    health: dict[str, Any] = slo.get("health") or {}
    nodes: dict[str, Any] = rep.get("nodes") or {}
    audit: dict[str, Any] = rep.get("audit") or {}
    audit_nodes: dict[str, Any] = audit.get("nodes") or {}
    lines = [
        f"ps top — {len(nodes)} node(s), window {window_s:.0f}s, "
        f"{time.strftime('%H:%M:%S')}",
        "",
        f"{'node':>5} {'role':<10} {'rank':>4} {'push/s':>9} "
        f"{'pull/s':>9} {'shed/s':>8} {'p99_push':>9} {'q_p99':>7} "
        f"{'age_p99':>8} {'health':>7} {'audit':>6}  alerts",
    ]
    def _row(nid: str, role: str, rank: str) -> str:
        s = series.get(nid) or {}
        rates = s.get("rates") or {}
        p99 = s.get("p99") or {}
        h = health.get(nid) or {}
        # a node is a client OR a server of each verb: show whichever
        # side of the wire it actually observed this window
        hr = s.get("hist_rates") or {}
        push_rate = _first(hr, "server.push", "client.push")
        pull_rate = _first(hr, "server.pull", "client.pull")
        shed_rate = rates.get("serve_shed", 0.0)
        p99_push = _first(p99, "server.push", "client.push")
        q_p99 = p99.get("server.apply_queue.n", 0.0)
        # realized data age of this node's serves (ms) — the freshness
        # plane's headline number (ISSUE 17)
        age_p99 = _first(p99, "serve.age_s", "serve.age")
        burning = ",".join(h.get("burning") or []) or "-"
        score = h.get("score")
        # the audit column: violations attributed to this node's event
        # stream; "ok" beats a zero so a clean column reads as a verdict
        an = audit_nodes.get(nid) or {}
        viol = int(an.get("violations") or 0)
        audit_cell = str(viol) if viol else ("ok" if an else "-")
        return (
            f"{nid:>5} {role:<10} "
            f"{rank:>4} {push_rate:>9.1f} "
            f"{pull_rate:>9.1f} {shed_rate:>8.1f} {p99_push:>9.2f} "
            f"{q_p99:>7.0f} {age_p99:>8.1f} "
            f"{(str(score) if score is not None else '-'):>7} "
            f"{audit_cell:>6}  {burning}"
        )

    for nid in sorted(nodes, key=lambda x: int(x)):
        info = nodes[nid]
        lines.append(_row(
            nid, str(info.get("role", "?")), str(info.get("rank", ""))
        ))
    if "coord" in series or "coord" in health:
        # the scheduler process itself: SSP blocked time and control-
        # plane counters live only here (it never heartbeats to itself)
        lines.append(_row("coord", "coordinator", "-"))
    alerts = slo.get("alerts") or []
    lines.append("")
    if alerts:
        lines.append(f"ACTIVE SLO ALERTS ({len(alerts)}):")
        for a in alerts:
            lines.append(
                f"  [{a['rule']}] node={a['node']} "
                f"burn_short={a['burn_short']}x burn_long={a['burn_long']}x"
            )
    else:
        lines.append("no active SLO alerts")
    total_viol = int(audit.get("total") or 0)
    if total_viol:
        lines.append("")
        lines.append(f"AUDIT VIOLATIONS ({total_viol}):")
        for v in (audit.get("recent") or [])[-5:]:
            lines.append(
                format_violation(v, exclude=("kind", "monitor", "at"))
            )
    elif audit:
        lines.append("audit: no protocol violations")
    # the latency-forensics line (ISSUE 15): the cluster's slowest push
    # of the window with its segment split (wire vs server vs apply,
    # from the reply's server-timing echo) and the tail-trace id that
    # links it to the retained trace — `cli whylate` is the deep dive
    slow = (rep.get("merged") or {}).get("slow") or {}
    worst = None
    for cmd in ("push", "pull"):
        recs = slow.get(cmd) or []
        if recs and (worst is None or recs[0].get(
            "dur_ms", 0.0
        ) > worst.get("dur_ms", 0.0)):
            worst = recs[0]
    if worst:
        seg = worst.get("seg") or {}
        parts = "  ".join(
            f"{k}={v}ms"
            for k, v in sorted(seg.items(), key=lambda kv: -kv[1])
        )
        lines.append("")
        lines.append(
            f"slowest {worst.get('cmd', '?')}: "
            f"{worst.get('dur_ms', 0.0)}ms"
            + (f"  {parts}" if parts else "")
            + (f"  tid={worst['tid']}" if worst.get("tid") else "")
        )
    # the freshness line (ISSUE 17): the window's stalest serve — the
    # worst realized data-age p99 across nodes, and the key range the
    # staleness concentrates in (`cli ranges` is the deep dive)
    stalest: tuple[str | None, float] = (None, 0.0)
    stale_rng: tuple[str | None, float] = (None, 0.0)
    for nid, s in series.items():
        for name, v in ((s or {}).get("p99") or {}).items():
            if canonical_series(name) == "serve.age_s" and v > stalest[1]:
                stalest = (nid, v)
            parsed = split_range_series(name)
            if parsed and parsed[1] == "age" and v > stale_rng[1]:
                stale_rng = (parsed[0], v)
    if stalest[0] is not None or stale_rng[0] is not None:
        bits = []
        if stalest[0] is not None:
            bits.append(f"node={stalest[0]} age_p99={stalest[1]}ms")
        if stale_rng[0] is not None:
            bits.append(f"range={stale_rng[0]} age_p99={stale_rng[1]}ms")
        lines.append("")
        lines.append("stalest serve: " + "  ".join(bits))
    heat = (rep.get("merged") or {}).get("key_heat")
    if heat:
        pairs = heat_top(heat, 5)
        if pairs:
            lines.append("")
            lines.append(
                "hot keys: "
                + "  ".join(f"{k}~{c}" for k, c in pairs)
            )
    prof = (rep.get("merged") or {}).get("prof")
    if prof:
        lines.append("")
        lines.append("hot stacks (cluster, sampled):")
        for p in prof[:3]:
            tail = ";".join(str(p.get("s", "")).split(";")[-3:])
            lines.append(f"  {p.get('n', 0):>6}  ...{tail}")
    return "\n".join(lines)


def ranges_view(rep: dict[str, Any], window_s: float) -> dict[str, Any]:
    """Aggregate a coordinator ``telemetry`` reply's per-node windowed
    series into ONE per-range traffic/freshness matrix (the `cli
    ranges` data model, also its ``--json`` document): rates sum across
    nodes (each node books its own contribution to a range's series);
    percentiles take the cross-node MAX (a summary carries no buckets
    to merge, and the worst node's p99 is the honest bound a dashboard
    wants); hot-key heat folds the merged count-min sketch onto the
    owning range."""
    series: dict[str, Any] = rep.get("series") or {}
    ranges: dict[str, dict[str, float]] = {}
    for s in series.values():
        s = s or {}
        for blk in ("rates", "hist_rates"):
            for name, v in (s.get(blk) or {}).items():
                parsed = split_range_series(name)
                if parsed is None:
                    continue
                d = ranges.setdefault(parsed[0], {})
                key = parsed[1] + "_rate"
                d[key] = round(d.get(key, 0.0) + float(v), 3)
        for blk in ("p50", "p99"):
            for name, v in (s.get(blk) or {}).items():
                parsed = split_range_series(name)
                if parsed is None:
                    continue
                d = ranges.setdefault(parsed[0], {})
                key = f"{parsed[1]}_{blk}_ms"
                d[key] = max(d.get(key, 0.0), float(v))
    rngs: list[tuple[int, int]] = []
    for rid in ranges:
        b, dash, e = rid.partition("-")
        if dash and b.isdigit() and e.isdigit():
            rngs.append((int(b), int(e)))
    rngs.sort()
    heat = (rep.get("merged") or {}).get("key_heat")
    if heat and rngs:
        for key, c in heat_top(heat, 32):
            own = owning_range(int(key), rngs)
            if own:
                rid = f"{own[1][0]}-{own[1][1]}"
                d = ranges.setdefault(rid, {})
                d["heat"] = d.get("heat", 0) + int(c)
    return {"window_s": window_s, "ranges": ranges}


def format_ranges(rep: dict[str, Any], window_s: float) -> str:
    """Render one ``cli ranges`` frame: the per-range matrix — push/pull
    rates, bytes moved, apply cost and the realized data-age
    distribution of serves touching each range — from a coordinator
    ``telemetry`` reply."""
    view = ranges_view(rep, window_s)
    ranges: dict[str, dict[str, float]] = view["ranges"]
    lines = [
        f"ps ranges — {len(ranges)} range(s), window {window_s:.0f}s, "
        f"{time.strftime('%H:%M:%S')}",
        "",
        f"{'range':<16} {'pull/s':>9} {'push/s':>9} {'out_B/s':>11} "
        f"{'in_B/s':>11} {'apply_p99':>10} {'age_p50':>9} {'age_p99':>9} "
        f"{'heat':>7}",
    ]

    def _rid_key(rid: str) -> tuple:
        b, _, _ = rid.partition("-")
        # numeric ranges in key order; the saturation fold ("other") and
        # anything unparsable sorts last
        return (0, int(b), rid) if b.isdigit() else (1, 0, rid)

    for rid in sorted(ranges, key=_rid_key):
        d = ranges[rid]
        lines.append(
            f"{rid:<16} {d.get('pull_rate', 0.0):>9.1f} "
            f"{d.get('push_rate', 0.0):>9.1f} "
            f"{d.get('pull_bytes_rate', 0.0):>11.0f} "
            f"{d.get('push_bytes_rate', 0.0):>11.0f} "
            f"{d.get('apply_p99_ms', 0.0):>10.2f} "
            f"{d.get('age_p50_ms', 0.0):>9.1f} "
            f"{d.get('age_p99_ms', 0.0):>9.1f} "
            f"{int(d.get('heat', 0)):>7}"
        )
    if not ranges:
        lines.append(
            "(no range series in the window — freshness plane idle)"
        )
    return "\n".join(lines)


def format_audit(rep: dict[str, Any]) -> str:
    """Render one ``cli audit`` frame from the coordinator's ``audit``
    reply (utils/auditor.py ``Auditor.summary``): stream accounting per
    node, violation totals by kind, and the recent-violations panel."""
    lines = [
        f"ps audit — {int(rep.get('total') or 0)} violation(s), "
        f"{int(rep.get('suppressed') or 0)} suppressed (holed stream), "
        f"{time.strftime('%H:%M:%S')}",
        "",
        f"{'node':>6} {'batches':>8} {'events':>8} {'gaps':>5} "
        f"{'dropped':>8} {'violations':>11}",
    ]
    for nk in sorted(rep.get("nodes") or {}):
        st = rep["nodes"][nk]
        lines.append(
            f"{nk:>6} {st.get('batches', 0):>8} {st.get('events', 0):>8} "
            f"{st.get('gaps', 0):>5} {st.get('dropped', 0):>8} "
            f"{st.get('violations', 0):>11}"
        )
    by_kind = rep.get("by_kind") or {}
    if by_kind:
        lines.append("")
        lines.append("violations by kind:")
        for kind in sorted(by_kind):
            lines.append(f"  {kind:<28} {by_kind[kind]}")
        lines.append("")
        lines.append("recent:")
        for v in rep.get("recent") or []:
            lines.append(format_violation(v))
    else:
        lines.append("")
        lines.append(
            "no protocol violations — monitors armed: "
            + ", ".join(rep.get("monitors") or [])
        )
    return "\n".join(lines)
