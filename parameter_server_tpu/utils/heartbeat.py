"""Heartbeats and failure detection.

Reference analog: node->scheduler heartbeats carrying CPU/mem/net stats
(system/heartbeat_info.h), the scheduler dashboard table, and dead-node
detection from missed heartbeats / transport disconnects.

Here hosts are processes in a pod: each runs a ``HeartbeatReporter`` thread
publishing stats into a shared ``HeartbeatMonitor`` (in-process for tests /
single host; multi-host transports can publish the same dicts through the
jax.distributed KV store). The monitor flags nodes whose last beat is older
than a timeout — the trigger for checkpoint-restart recovery."""

from __future__ import annotations

import os
import threading
import time

from parameter_server_tpu.utils import flightrec


def host_stats() -> dict:
    """CPU/mem snapshot for this process (ref: heartbeat_info fields)."""
    out: dict = {"pid": os.getpid(), "time": time.time()}
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        out["max_rss_mb"] = ru.ru_maxrss / 1024.0
        out["utime_s"] = ru.ru_utime
        out["stime_s"] = ru.ru_stime
    except Exception:  # pragma: no cover - platform-specific
        pass
    try:
        out["load1"] = os.getloadavg()[0]
    except OSError:  # pragma: no cover
        pass
    return out


class HeartbeatMonitor:
    """Scheduler-side registry of last-seen beats (thread-safe).

    Beyond the latest beat, the monitor RETAINS each node's telemetry
    stream (ISSUE 13): every piggybacked ``telemetry`` snapshot feeds a
    per-node bounded :class:`~parameter_server_tpu.utils.timeseries.
    TimeSeriesRing` of deltas stamped at receive time (receive-time
    stamping is clock-skew-proof; the beat cadence bounds the error).
    That history — not the point sample — is what the coordinator's
    windowed ``telemetry`` view, ``cli top`` and the ``[slo]`` burn-rate
    engine read."""

    def __init__(self, timeout_s: float = 30.0, series_capacity: int = 360):
        self.timeout_s = timeout_s
        self.series_capacity = series_capacity
        self._beats: dict[int, dict] = {}
        self._series: dict[int, "TimeSeriesRing"] = {}
        self._lock = threading.Lock()

    def beat(self, node_id: int, stats: dict | None = None) -> None:
        self.beat_many([(node_id, stats)])

    def beat_many(self, items: list[tuple[int, dict | None]]) -> None:
        """Record a whole batch of beats under ONE lock acquisition (the
        coordinator's batched ingest drain): at cluster scale the beat
        stream is the monitor's hottest writer, and per-frame acquires
        made it contend with every dead()/alive() sweep."""
        from parameter_server_tpu.utils.timeseries import TimeSeriesRing

        now = time.monotonic()
        wall = time.time()
        feeds: list[tuple["TimeSeriesRing", dict]] = []
        with self._lock:
            for node_id, stats in items:
                self._beats[node_id] = {"t": now, "stats": stats or {}}
                tel = (stats or {}).get("telemetry")
                if tel:
                    ring = self._series.get(node_id)
                    if ring is None:
                        ring = self._series[node_id] = TimeSeriesRing(
                            self.series_capacity
                        )
                    feeds.append((ring, tel))
        # delta computation (O(series) dict diffing per beat) happens
        # OUTSIDE the monitor lock — the beat stream is this lock's
        # hottest writer and must not serialize against dead()/alive()
        # sweeps. Rings lock themselves; a racing out-of-order observe
        # is discarded by the ring's monotonic-ts check (beats are
        # last-writer-wins telemetry).
        for ring, tel in feeds:
            ring.observe(tel, ts=wall)

    def node_series(self) -> dict[int, "TimeSeriesRing"]:
        """Per-node retained telemetry rings (live references — ring
        reads are internally thread-safe)."""
        with self._lock:
            return dict(self._series)

    def alive(self) -> list[int]:
        now = time.monotonic()
        with self._lock:
            return sorted(
                n for n, b in self._beats.items() if now - b["t"] <= self.timeout_s
            )

    def dead(self) -> list[int]:
        """Nodes that have beaten before but are now overdue (ref: the
        dead-node list driving recovery)."""
        now = time.monotonic()
        with self._lock:
            return sorted(
                n for n, b in self._beats.items() if now - b["t"] > self.timeout_s
            )

    def latest_stats(self) -> dict[int, dict]:
        """Last-reported stats per node (the telemetry plane's raw feed:
        nodes piggyback counter/histogram snapshots on their beats)."""
        with self._lock:
            return {n: dict(b["stats"]) for n, b in self._beats.items()}

    def forget(self, node_id: int) -> None:
        """Drop a node's record once its death has been *handled* (workloads
        requeued, clock retired) or it finished cleanly: ``dead()`` stays
        the actionable list instead of accumulating corpses that would
        re-trigger recovery every sweep. A late beat from a falsely-flagged
        node simply re-registers it."""
        with self._lock:
            self._beats.pop(node_id, None)
            self._series.pop(node_id, None)

    def dashboard(self) -> str:
        """The scheduler's cluster table (ref: dashboard printout)."""
        now = time.monotonic()
        with self._lock:
            lines = [f"{'node':>6} {'age_s':>8} {'rss_mb':>8} {'load1':>6}"]
            for n in sorted(self._beats):
                b = self._beats[n]
                s = b["stats"]
                lines.append(
                    f"{n:>6} {now - b['t']:>8.1f} "
                    f"{s.get('max_rss_mb', float('nan')):>8.1f} "
                    f"{s.get('load1', float('nan')):>6.2f}"
                )
        return "\n".join(lines)


class HeartbeatReporter:
    """Per-node thread beating into a monitor every ``interval_s``.

    ``stats_fn`` builds each beat's stats payload (default: host_stats);
    the multi-process tier passes a function that piggybacks the node's
    telemetry snapshot so the scheduler's cluster view needs no second
    collection path (ref: heartbeat_info carrying the dashboard stats)."""

    def __init__(
        self,
        monitor: HeartbeatMonitor,
        node_id: int,
        interval_s: float = 5.0,
        stats_fn=host_stats,
    ):
        self.monitor = monitor
        self.node_id = node_id
        self.interval_s = interval_s
        self._stats_fn = stats_fn
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        #: completed beats — the watchdog's heartbeat-silence progress
        #: probe (a beat thread wedged in a dead sink stops advancing it)
        self.beats = 0

    def start(self) -> "HeartbeatReporter":
        self._beat_once()  # immediate first beat
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="ps-heartbeat"
        )
        self._thread.start()
        return self

    def _beat_once(self) -> None:
        stats = self._stats_fn()
        # audit plane (ISSUE 14): the beat carries this process's spooled
        # audit events as seq-numbered batches and acks them only after a
        # successful send — a beat that dies on the wire leaves them
        # in-flight and the NEXT beat re-ships the same seqs (the
        # coordinator's auditor dedups by (node, seq), so at-least-once
        # delivery here never double-counts there)
        spool = flightrec.audit_spool()
        if spool is not None and isinstance(stats, dict):
            batches = spool.drain()
            if batches:
                stats["audit"] = batches
        # a sink returning False reports delivery failure (the remote
        # RPC sink); None (the in-process monitor) means delivered
        ok = self.monitor.beat(self.node_id, stats)
        if spool is not None and ok is not False:
            spool.ack()
        self.beats += 1
        flightrec.record("heartbeat.beat", node=self.node_id, n=self.beats)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._beat_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
