"""The live audit plane's coordinator half: a streaming auditor over
the cluster event bus.

Nodes spool audit-relevant flight-recorder events
(utils/flightrec.py ``EventSpool``) and ship them as sequence-numbered
batches on their heartbeat piggyback; this module ingests those
batches at the coordinator — per-node seq dedup (re-shipped batches
from a failed beat are dropped, not double-counted), gap and
saturation accounting — and runs every event through the shared
streaming monitors (analysis/monitors.py), the SAME automata
``cli postmortem`` feeds offline. A violation:

- fires an ``audit.violation`` flight-recorder event (so it lands in
  the coordinator's black box and the postmortem renders it),
- bumps ``audit_violations`` (the coordinator's own time-series ring
  carries it, so the shipped ``[slo]`` rule pages on a sustained
  violation stream with no extra plumbing),
- lands in the bounded recent-violations panel ``cli top`` and
  ``cli audit`` render.

**Evidence discipline**: the online plane never bluffs. Pairing-based
verdicts (acked-but-unapplied, SSP staleness) are SUPPRESSED — counted
in ``audit_suppressed``, not raised — while a stream that could hold
the missing half of the pair has known holes (that node's spool
saturated, or its batch seqs jumped), because "the commit never
arrived" and "the commit was dropped on the floor" are different
facts. Holes are tracked PER NODE with the roles the coordinator
knows, so the targeting is as tight as the evidence allows: an
acked-but-unapplied verdict is suppressed only while a *server*
stream (or a role-unknown stream other than the acking node's own) is
holed — the missing commit could only live there; an SSP verdict only
while the clock-owning stream itself is holed. One busy worker
saturating its spool therefore cannot blind the auditor to violations
whose evidence lives entirely in clean streams. Self-contained
verdicts (version regressions, double applies, heal divergence, shed
storms) stay live regardless: a hole can only make them false
negatives, never false positives.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

from parameter_server_tpu.utils import flightrec
from parameter_server_tpu.utils.metrics import wire_counters

#: verdicts that pair facts across nodes — the ones a holed stream
#: could turn into false alarms (see the module docstring)
_SUPPRESSIBLE = frozenset({"acked-but-unapplied", "ssp-staleness"})

#: violation fields forwarded into the audit.violation event (scalars
#: only — the flight-recorder contract keeps dump rows small)
_EVENT_FIELDS = ("cid", "seq", "worker", "step", "from", "to", "count")


class Auditor:
    """Coordinator-side streaming monitor harness (thread-safe)."""

    def __init__(self, cfg: "AuditConfig | None" = None):
        from parameter_server_tpu.analysis import monitors as monitors_mod
        from parameter_server_tpu.utils.config import AuditConfig

        self.cfg = cfg or AuditConfig()
        self._monitors = monitors_mod.make_monitors(
            watermark_s=self.cfg.watermark_s,
            heal_timeout_s=self.cfg.heal_timeout_s,
            shed_storm_n=self.cfg.shed_storm_n,
            shed_storm_window_s=self.cfg.shed_storm_window_s,
        )
        self._by_event: dict[str, list] = {}
        for m in self._monitors:
            for et in m.EVENTS:
                self._by_event.setdefault(et, []).append(m)
        self._lock = threading.Lock()
        #: per-node stream accounting: last seq, event/batch counts,
        #: the spool's cumulative drop watermark, seq gaps, violations
        self._nodes: dict[str, dict[str, Any]] = {}
        self._recent: deque[dict[str, Any]] = deque(
            maxlen=max(int(self.cfg.recent), 8)
        )
        self._by_kind: dict[str, int] = {}
        self.total = 0
        self.suppressed = 0
        #: per-node stream holes (feeder-supplied now) + known roles —
        #: the targeting data for pairing-verdict suppression
        self._holes: dict[str, float] = {}
        self._roles: dict[str, str] = {}
        #: the auditor's notion of "now": the max feeder-supplied clock
        #: (wall time in production, test-supplied in drills) — summary
        #: reads it so hole windows stay in ONE clock domain
        self._clock = 0.0

    # -- ingest ------------------------------------------------------------

    def _node(self, node: str) -> dict[str, Any]:
        st = self._nodes.get(node)
        if st is None:
            st = self._nodes[node] = {
                "seq": -1, "batches": 0, "events": 0,
                "dropped": 0, "gaps": 0, "violations": 0,
            }
        return st

    def ingest(
        self,
        node: Any,
        batches: list[dict[str, Any]],
        now: float | None = None,
        role: str | None = None,
    ) -> int:
        """Feed one node's piggybacked batches; returns violations
        raised. Batches are deduped by seq per node (at-least-once
        delivery upstream); gaps and saturation are booked as THAT
        node's stream holes, which suppress the pairing-based verdicts
        whose missing half could live in it. ``role`` (the coordinator
        knows it from the registry) tightens the targeting."""
        if now is None:
            now = time.time()
        nk = str(node)
        found = 0
        fed = 0
        with self._lock:
            self._clock = max(self._clock, now)
            if role:
                self._roles[nk] = role
            st = self._node(nk)
            for batch in sorted(
                batches or (), key=lambda b: int(b.get("seq", 0))
            ):
                try:
                    seq = int(batch["seq"])
                    events = batch["events"]
                except (KeyError, TypeError, ValueError):
                    continue  # a torn batch is a hole, not a crash
                if seq <= st["seq"]:
                    continue  # re-shipped after a lost beat ack: dup
                if st["seq"] >= 0 and seq > st["seq"] + 1:
                    st["gaps"] += 1
                    self._holes[nk] = now
                    wire_counters.inc("audit_gaps")
                st["seq"] = seq
                dropped = int(batch.get("dropped", 0))
                if dropped > st["dropped"]:
                    st["dropped"] = dropped
                    self._holes[nk] = now  # spool saturated: holes
                st["batches"] += 1
                wire_counters.inc("audit_batches")
                for raw in events:
                    try:
                        ts, _tid, etype, fields = raw
                    except (TypeError, ValueError):
                        continue
                    mons = self._by_event.get(etype)
                    if not mons:
                        continue
                    st["events"] += 1
                    fed += 1
                    ev = {
                        "ts": float(ts), "life": nk, "etype": etype,
                        "args": fields or {}, "at": now,
                    }
                    for m in mons:
                        for v in m.feed(ev):
                            found += self._emit(v, now)
            if fed:
                wire_counters.inc("audit_events", fed)
        return found

    def flush(self, now: float | None = None) -> int:
        """Watermark pass (the coordinator sweep cadence): expire
        unpaired facts into violations."""
        if now is None:
            now = time.time()
        found = 0
        with self._lock:
            self._clock = max(self._clock, now)
            for m in self._monitors:
                for v in m.flush(now):
                    found += self._emit(v, now)
        return found

    def finish(self, now: float | None = None) -> int:
        """End-of-stream (tests / offline parity): judge everything."""
        if now is None:
            now = time.time()
        found = 0
        with self._lock:
            self._clock = max(self._clock, now)
            for m in self._monitors:
                for v in m.finish():
                    found += self._emit(v, now)
        return found

    def set_ssp(self, num_workers: int, max_delay: int) -> None:
        """Teach the SSP monitor the clock's bound (from ssp_init)."""
        with self._lock:
            for m in self._monitors:
                if hasattr(m, "set_bounds"):
                    m.set_bounds(max_delay, num_workers)

    # -- verdicts ----------------------------------------------------------

    def _holed_nodes(self, now: float) -> list[str]:
        horizon = 2 * self.cfg.watermark_s
        return [
            n for n, t in self._holes.items() if now - t < horizon
        ]

    def _evidence_holed(self, v: dict[str, Any], now: float) -> bool:
        """Could the verdict's MISSING pairing half live in a currently
        holed stream? (the per-kind targeting in the module docstring)"""
        holed = self._holed_nodes(now)
        if not holed:
            return False
        life = str(v.get("life", ""))
        if v["kind"] == "ssp-staleness":
            # the justifying ssp.finish lives in the SAME stream as the
            # wait that raised the suspicion (the clock owner's)
            return life in holed
        # acked-but-unapplied: the ack survived (it is the evidence);
        # the missing commit lives in a SERVER stream — a holed stream
        # only suppresses if it is one (or its role is unknown) and is
        # not the acking node's own
        return any(
            n != life and self._roles.get(n, "server") in ("server", "")
            for n in holed
        )

    def _emit(self, v: dict[str, Any], now: float) -> int:
        """Book one monitor violation (caller holds the lock); returns
        1 if raised, 0 if suppressed for lack of stream evidence."""
        kind = v["kind"]
        if kind in _SUPPRESSIBLE and self._evidence_holed(v, now):
            self.suppressed += 1
            wire_counters.inc("audit_suppressed")
            return 0
        self.total += 1
        self._by_kind[kind] = self._by_kind.get(kind, 0) + 1
        life = v.get("life")
        nk = str(life) if life is not None else ""
        if nk in self._nodes:
            self._nodes[nk]["violations"] += 1
        wire_counters.inc("audit_violations")
        fields = {
            k: v[k] for k in _EVENT_FIELDS if v.get(k) is not None
        }
        flightrec.record(
            "audit.violation", kind=kind, monitor=v.get("monitor", ""),
            node=nk, **fields,
        )
        self._recent.append({**v, "at": round(now, 3)})
        return 1

    # -- reads -------------------------------------------------------------

    def summary(self, recent: int = 20) -> dict[str, Any]:
        """The ``cli audit`` / ``cli top`` / telemetry block."""
        with self._lock:
            return {
                "total": self.total,
                "suppressed": self.suppressed,
                "by_kind": dict(sorted(self._by_kind.items())),
                "nodes": {n: dict(st) for n, st in self._nodes.items()},
                "recent": list(self._recent)[-max(int(recent), 0):],
                "monitors": sorted(m.name for m in self._monitors),
                # which streams currently degrade pairing verdicts —
                # the operator's "why is detection suppressed" answer
                "holed": sorted(self._holed_nodes(self._clock)),
            }

    def violations(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._recent)
