"""Canonical clock helpers: every timestamp names its clock AND unit.

pslint v3 (ISSUE 20) types every value with its quantity: the
``clockdomain`` checker tags timestamps by source clock (wall /
monotonic / perf_counter / a PEER's wall echoed through a wire field)
and flags cross-domain mixing, and the ``units`` checker tracks the
us/ms/s lattice. Bare ``time.time()`` calls defeat both half the time:
the call itself is typed (wall, seconds) but the first un-suffixed
local it lands in drops the unit. These wrappers bake clock and unit
into the NAME the dataflow reads (``now_wall_us`` -> ck:wall + u:us),
so call sites stay typed for free. New code takes its timestamps here;
``time.*`` stays fine in tests and one-off scripts.

``skew_clamped_age_s`` is the one sanctioned place a foreign-wall
publish timestamp (a peer-echoed ``pts``) meets the local wall clock:
"clamp" in the name declares it to the clockdomain checker, and the
floor-at-0 IS the skew handling (PR 14's lesson — cross-node wall
deltas go negative, and negative age must never reach a histogram).
"""

from __future__ import annotations

import time

__all__ = [
    "now_mono_s",
    "now_mono_us",
    "now_perf_s",
    "now_wall_s",
    "now_wall_us",
    "skew_clamped_age_s",
]


def now_wall_s() -> float:
    """Wall-clock epoch seconds (``time.time``): the only clock that is
    meaningful ACROSS processes — and only modulo NTP skew, so wall
    deltas taken against a peer's stamp go through a skew clamp."""
    return time.time()


def now_wall_us() -> int:
    """Wall-clock epoch microseconds (the wire/publish-ts granularity:
    binary-header i64 slots and the RCU publish tuple carry these)."""
    return int(time.time() * 1e6)


def now_mono_s() -> float:
    """Monotonic seconds (``time.monotonic``): in-process intervals —
    deadlines, backoff, cache residence. Never crosses a process."""
    return time.monotonic()


def now_mono_us() -> int:
    """Monotonic microseconds, for µs-granular in-process intervals."""
    return int(time.monotonic() * 1e6)


def now_perf_s() -> float:
    """High-resolution perf counter seconds (``time.perf_counter``):
    micro-benchmark timing inside one process."""
    return time.perf_counter()


def skew_clamped_age_s(pts_us: float) -> float:
    """Realized age (seconds) of a µs-epoch publish timestamp against
    THIS process's wall clock, floored at 0: when ``pts_us`` came from
    a peer (or an NTP step landed between publish and serve), the raw
    difference can be negative by the cross-node skew — a negative age
    is clamped, never booked."""
    return max(time.time() - float(pts_us) / 1e6, 0.0)
