"""Typed configuration (reference analog: gflags + protobuf text-format configs).

The reference splits config in two tiers (ref: src/main.cc gflags for
topology; src/app/linear_method/proto/linear_method.proto for the app).
Here the same inventory of fields lives in dataclasses, loadable from
JSON or TOML. Field names are kept close to the reference's proto fields
(``minibatch``, ``max_delay``, ``lambda_l1`` ...) so parity is auditable.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any


@dataclass
class DataConfig:
    """Ref: linear_method.proto DataConfig {format, file, ignore_feature_group}."""

    files: list[str] = field(default_factory=list)
    format: str = "libsvm"  # libsvm | criteo | adfea | cache
    num_keys: int = 1 << 22  # dense hashed key-space size (power of two + pad row)
    val_files: list[str] = field(default_factory=list)
    max_nnz_per_example: int = 512
    cache_dir: str = ""  # columnar block cache (ref: SlotReader cache)
    # frequency-filter admission (ref: parameter/frequency_filter.h): only
    # keys seen >= this many times enter batches; 0 disables. Sketch
    # geometry comes from the [sketch] section. Applies to the streaming
    # (SGD/FTRL) ingest; eval always sees all keys (unadmitted ones simply
    # carry zero weight).
    freq_min_count: int = 0
    # host input pipeline depth (ref: learner/sgd.h parser threads +
    # threadsafe queues): bound of the prefetch queues feeding the SPMD
    # dispatch loop; 0 builds batches serially inline (debugging)
    pipeline_depth: int = 2
    # bucketed static shapes (TPU idiom): pad batch entry/unique arrays to
    # the next power of two above the real count instead of the
    # max_nnz_per_example worst case — host->device bytes track actual
    # density; jit compiles once per bucket (a handful of shapes).
    # Default ON: measured 3.5x e2e on TPU (BENCH_r03_local.json ladder,
    # 4.1k -> 14.2k ex/s) and 5.8x on CPU (BENCH_r04 ladder) with AUC
    # unchanged (0.854) in both — see BASELINE.md "default promotions"
    bucket_nnz: bool = True
    # compact wire format (on by default): int32 keys + (B+1,) row_splits
    # instead of (NNZ,) row_ids on the host->device transfer — ~40% fewer
    # bytes at typical densities; the device rebuilds row ids with one
    # searchsorted. False ships the full row_ids (debugging / parity runs)
    compact_wire: bool = True
    # feature-value dtype on the host->device wire: "f32" (exact, default)
    # or "f16" — half the value bytes; IEEE round-to-nearest quantization,
    # cast back to f32 on-device before compute (the reference's
    # fixing_float filter applied to the H2D feed instead of the
    # server wire). Binary/one-hot features (criteo cats, adfea) are
    # exactly representable; log1p-scaled ints lose <0.1% relative.
    wire_values: str = "f32"


@dataclass
class LearningRateConfig:
    """Ref: learning_rate.h — alpha/beta as in the FTRL paper."""

    alpha: float = 0.1
    beta: float = 1.0
    eta: float = 0.1  # plain SGD step size
    decay: float = 0.0


@dataclass
class PenaltyConfig:
    """Ref: penalty.h — elastic net."""

    lambda_l1: float = 1.0
    lambda_l2: float = 0.0


@dataclass
class SolverConfig:
    """Ref: linear_method.proto solver settings (sgd/ftrl/darlin)."""

    algo: str = "ftrl"  # ftrl | adagrad | sgd | darlin
    minibatch: int = 4096
    max_delay: int = 0  # SSP bounded delay tau; 0 => BSP, <0 => fully async
    # microsteps scanned per device call (TPU idiom for the reference's
    # bounded-delay pipelining of many small Push/Pull tasks): K > 1 runs K
    # SEQUENTIAL parameter-server steps inside one jitted program — one
    # host->device transfer, one dispatch, one retirement per K steps —
    # amortizing the per-call round-trip floor that dominates on tunneled
    # or dispatch-bound hosts. Same trajectory as K single-step calls;
    # max_delay then counts device CALLS in flight (each K steps deep).
    # Honored by the linear_method path (PodTrainer) and the word2vec and
    # matrix_fac apps (steps_per_call=..., wired from this field by the CLI).
    steps_per_call: int = 1
    epochs: int = 1
    # darlin-only:
    block_iters: int = 20
    feature_blocks: int = 16
    # distributed darlin data residency: 0 keeps all packed blocks in HBM
    # (device_put once); C > 0 streams C blocks at a time from the block
    # cache (bounded memory; ref: SlotReader streams per block)
    block_chunk: int = 0
    kkt_filter_threshold: float = 0.0  # 0 disables the KKT filter
    epsilon: float = 1e-4  # relative-objective stopping rule


@dataclass
class GraphConfig:
    """graph_partition app settings (ref: the graph_partition App config)."""

    num_partitions: int = 8
    balance_penalty: float = 1.0


@dataclass
class MFConfig:
    """matrix_fac app settings (ref: the MF app's config; BASELINE's
    MovieLens parity config). data.files = 'user item rating' text."""

    num_users: int = 1000
    num_items: int = 1000
    rank: int = 64
    eta: float = 0.05
    l2: float = 0.01
    algo: str = "adagrad"  # adagrad | sgd
    batch_size: int = 4096
    block_lines: int = 1 << 20  # streaming shuffle-block size


@dataclass
class W2VConfig:
    """word2vec app settings (ref: BASELINE's SGNS parity config).
    data.files = whitespace-separated token-id text (or .npy)."""

    vocab_size: int = 1 << 16
    dim: int = 64
    window: int = 2
    negatives: int = 5
    eta: float = 0.3
    batch_size: int = 8192
    block_tokens: int = 1 << 20


@dataclass
class WDConfig:
    """wide_deep app settings (ref: BASELINE's "Wide-&-Deep CTR with
    100M-row embedding table" parity config). The wide half reuses the
    [lr]/[penalty] FTRL hyperparameters; fields here shape the deep half.
    data.files = criteo/libsvm/adfea text like the linear app."""

    emb_dim: int = 16
    hidden: list[int] = field(default_factory=lambda: [32, 16])
    emb_eta: float = 0.05  # AdaGrad step for the embedding table
    mlp_lr: float = 1e-3  # Adam step for the dense MLP


@dataclass
class SketchConfig:
    """sketch app settings (ref: the sketch App — distributed count-min)."""

    width: int = 1 << 20
    depth: int = 4
    min_count: int = 2  # heavy-hitter admission threshold


@dataclass
class FilterConfig:
    """Ref: the per-task FilterConfig protos (src/filter/). On-pod traffic
    needs none of these (static layouts over ICI); they apply to the
    cross-process wire tier (parallel/control, parallel/multislice)."""

    key_caching: bool = True  # ref: filter/key_caching.h signatures
    compressing: bool = False  # ref: filter/compressing.h (zlib here)
    fixing_float_bytes: int = 0  # ref: filter/fixing_float.h; 0 off, 1|2 bytes


@dataclass
class WireConfig:
    """Async pipelined RPC data plane (parallel/control.py): the wire-tier
    analog of the reference's bounded per-connection send window."""

    # in-flight seq-numbered requests per RpcClient connection; 1 restores
    # the old lockstep request-reply discipline
    window: int = 8
    # bound on whole STEPS of in-flight pushes a wire-tier worker may hold
    # before blocking (run_worker's PushWindow); 0 derives the bound purely
    # from solver.max_delay, so SSP semantics alone shape the window
    max_inflight_pushes: int = 0
    # derive the EFFECTIVE in-flight window from the client latency
    # histograms at runtime (shrink on p99 blowup, grow back while healthy
    # and saturated); ``window`` stays the hard ceiling. Off by default:
    # a fixed window is deterministic and the adaptation is a tail-latency
    # guard, not a throughput feature.
    adaptive_window: bool = False
    # RPC header codec: "bin" (versioned fixed-layout binary header,
    # negotiated per connection — a peer that never confirms binary
    # support keeps receiving JSON) or "json" (wire format of PRs 0-3,
    # always understood)
    hdr_codec: str = "bin"
    # quantized push transport (filters/quant.py): "off" sends float32
    # gradients; "int8"/"int16" sends per-segment-scale quantized
    # payloads with client-side error-feedback accumulators folding each
    # push's quantization residual into the next. Negotiated per
    # connection (the _feat advert, like the binary-header _bh): against
    # a server that never acks quant support the client transparently
    # stays on the float path — mixed clusters degrade, never corrupt.
    quant: str = "off"
    # quantizer segment length: one float32 scale rides the wire per this
    # many gradient coordinates (256 => ~1.6% scale overhead on int8)
    quant_seg: int = 256
    # also quantize PULL replies (read-mostly/serving traffic): the
    # server encodes the requested rows at the negotiated width. Off by
    # default — pulls have no error-feedback loop, so this trades exact
    # weight reads for wire bytes and belongs to serving tiers, not
    # training convergence paths.
    quant_pull: bool = False


@dataclass
class ServerConfig:
    """Shard-server batched apply engine (parallel/multislice.py): a
    dedicated apply thread drains a bounded queue of decoded pushes and
    coalesces everything concurrently arrived into ONE segment-summed
    updater apply, while pulls serve from an RCU-published snapshot."""

    # bound of the decoded-push apply queue; 0 disables the engine
    # entirely (pushes apply inline under the write lock — the serial
    # pre-engine discipline, kept as the bench baseline)
    apply_queue: int = 256
    # max pushes coalesced into one updater apply
    max_batch: int = 64
    # scale the EFFECTIVE batch ceiling to the observed arrival rate
    # instead of always draining up to max_batch: the ceiling doubles
    # while batches fill and the queue stays hot, halves when arrivals
    # go sparse (adaptations counted in ``server_batch_adapts``).
    # ``max_batch`` stays the hard ceiling.
    adaptive_batch: bool = False
    # reply-coalescing lane bounds, in withheld frames per connection:
    # control replies (the hi lane) flush at lane_hi, bulk pull/push
    # replies (the lo lane) at lane_lo
    lane_hi: int = 4
    lane_lo: int = 16
    # byte bound on withheld coalesced replies per connection: pull
    # replies pin their row arrays while withheld, so the lo lane also
    # flushes once this many MiB accumulate
    withheld_max_mb: int = 8


@dataclass
class ServeConfig:
    """Online serving plane (read-mostly pull traffic): client-side
    versioned key caching inside ``ServerHandle`` (generalizing the
    reference's key-cache filter to VALUES), server-side single-flight
    pull-encode coalescing, and admission control that sheds cache-backed
    pulls before the apply engine starves. Servers always speak the
    protocol (versions + not-modified replies cost nothing); the CLIENT
    cache arms only on handles constructed with ``serving=True`` AND
    ``cache = true`` — the training tier always bypasses it, because a
    trainer's staleness is bounded by the SSP clock, not a TTL."""

    # arm the client-side versioned key cache on serving handles
    cache: bool = False
    # serve a cached entry locally (no wire traffic at all) while younger
    # than this; past it the entry revalidates with an if_newer pull
    # (a not-modified reply re-arms the TTL without moving row bytes)
    ttl_ms: int = 50
    # HARD staleness ceiling: a shed revalidation may keep serving the
    # cached entry only while it is younger than this — past it the
    # client withholds shed_ok and the server must serve real rows, so
    # no client ever observes staleness beyond max(ttl, max_stale)
    max_stale_ms: int = 500
    # cached key-set entries per handle (LRU; invalidation is exact, so
    # eviction is a perf knob, never a correctness one)
    cache_entries: int = 1024
    # server: a key-set signature becomes HOT (its encoded pull reply is
    # cached and shared single-flight across clients at one version)
    # after this many pulls; higher keeps one-off training sweeps out of
    # the encode cache
    hot_min_pulls: int = 2
    # server: encoded-reply cache entries (per (sig, version, codec));
    # 0 disables pull coalescing entirely
    encode_cache_entries: int = 256
    # byte bound on the encoded-reply cache (each entry pins its reply
    # payload arrays): LRU-evicts past this many MiB, so a training
    # server with multi-MB pulls can't pin entries x payload of memory
    # for encodes that version churn will never let it reuse
    encode_cache_mb: int = 64
    # server: materialize a full host weights snapshot per version (the
    # serving read path: hot pulls become numpy fancy-indexing instead
    # of per-request jax dispatch) only while the shard's key range is
    # within this bound — a huge training shard must never pay a
    # full-table device->host sync for one read. 0 disables snapshots.
    snapshot_keys_max: int = 1 << 22
    # admission control: shed cache-backed pulls (the client advertised a
    # fallback via shed_ok) once the apply queue is this deep; 0 off
    shed_queue_depth: int = 0
    # ... or once this server's withheld coalesced-reply bytes (the lo
    # lane pinning pull payloads) cross this many MiB; 0 off
    shed_withheld_mb: int = 0
    # rides shed replies: how long the client should serve its cached
    # entry before revalidating again
    retry_after_ms: int = 20


@dataclass
class ParallelConfig:
    """Mesh topology: the TPU analog of -num_servers / -num_workers."""

    kv_shards: int = 1  # 'kv' mesh axis: range-sharded state (servers)
    data_shards: int = 1  # 'data' mesh axis: example shards (workers)
    # "per_worker": each worker's push is its own server updater step
    # (reference semantics); "aggregate": pre-sum grads across workers with
    # one psum and update once (exact for linear SGD); "quantized":
    # per_worker semantics with int8 grads on the wire (stochastic
    # rounding; the fixing_float filter as a quantized collective for
    # DCN-limited pods). See parallel/spmd.py.
    push_mode: str = "per_worker"


@dataclass
class MeshConfig:
    """Transport-neutral client data plane (parallel/backend.py): which
    KV backend apps written against ``PSBackend`` bind to. "socket" is
    the cross-process wire tier (ShardServer + ServerHandle, every
    filter/recovery feature of PRs 1-7); "mesh" is the in-mesh GSPMD
    tier (parallel/meshbackend.py) — the KV table is one NamedSharding-
    sharded array over the kv axis and push/pull lower to collectives
    over ICI instead of loopback sockets. Rule of thumb: co-located
    workers+servers in ONE JAX process mesh want "mesh"; anything
    crossing a process/DCN boundary stays "socket"."""

    backend: str = "socket"  # socket | mesh
    # kv-axis width of the mesh backend's table sharding; 0 = every
    # local device (the whole-host mesh)
    kv_shards: int = 0
    # quantized push collective (filters/quant.py fused into the sharded
    # update, EQuARX-style): "off" moves f32 gradients onto the mesh;
    # "int8"/"int16" move per-segment-scale integer payloads with the
    # client error-feedback residual preserved (the PR-6 win surviving
    # the transport change)
    quant: str = "off"
    # quantizer segment length (one f32 scale per this many coordinates)
    quant_seg: int = 256


@dataclass
class FaultConfig:
    """Failure detection / recovery knobs for the multi-process tier
    (ref: heartbeat_info + the scheduler's dead-node handling)."""

    heartbeat_interval_s: float = 2.0  # node -> scheduler beat cadence
    heartbeat_timeout_s: float = 10.0  # overdue beats mark a node dead
    straggler_reassign_s: float = 0.0  # age-based workload requeue; 0 off
    startup_grace_s: float = 60.0  # rank never registered by then => dead
    # server recovery (ref: checkpoint-based hot recovery; SURVEY §5.3/§5.4):
    server_ckpt_interval_s: float = 0.0  # periodic range dumps; 0 off
    # dead server: 0 = fail fast (unrecoverable); > 0 = tolerate this many
    # seconds for a relaunched server to re-register from its checkpoint
    server_restart_grace_s: float = 0.0
    reconnect_timeout_s: float = 60.0  # worker retry window per lost server
    # coordinator recovery sweep: dead workers' shards requeued + SSP clock
    # retired every this many seconds (0 disables the sweep thread)
    recovery_sweep_interval_s: float = 0.5
    # fault injection (parallel/chaos.py): a FaultPlan spec armed on every
    # RpcServer this config spawns (coordinator + shard servers); "" = off.
    # The PS_FAULT_PLAN / PS_FAULT_SEED env vars arm the same plans on
    # processes this config never reaches (spawned children).
    fault_plan: str = ""
    fault_seed: int = 0


@dataclass
class TraceConfig:
    """Distributed tracing (utils/trace.py). ``trace_dir`` arms span
    capture + Chrome trace-event export (open in Perfetto) on every
    process this config reaches; the ``PS_TRACE_DIR`` env var arms
    processes the config never touches (spawned children — the
    PS_FAULT_PLAN inheritance pattern)."""

    trace_dir: str = ""  # "" = tracing disabled (the free no-op path)
    capacity: int = 65536  # span ring-buffer bound per process
    # head-based trace sampling: record 1/N of TRACES (not spans), keyed
    # off the trace id so the decision is consistent for every span of
    # one logical operation across every process it touches — always-on
    # tracing at production step rates keeps whole traces, never
    # fragments. 1 (default) records everything.
    sample: int = 1
    # tail-biased capture (ISSUE 15): head-dropped traces buffer until
    # completion and PROMOTE past the sampler when they land in the
    # slowest-K per cmd, carry anomaly events, or breach the live
    # windowed p99 — so `sample = N` keeps exactly the traces a tail-
    # latency investigation needs. On by default wherever tracing is
    # armed (run_node / the train path); disable to get the pure
    # head-sampled stream back.
    tail: bool = True
    tail_k: int = 4  # slowest-K retained per root-span name per window
    tail_limbo: int = 8192  # limbo ring bound (events) for the sidecar


@dataclass
class BlackboxConfig:
    """Black-box flight recorder + stall watchdog + postmortem dumps
    (utils/flightrec.py). ``dir`` arms the always-on ring recorder and
    the per-process watchdog on every process this config reaches; the
    ``PS_BLACKBOX_DIR`` env var arms processes the config never touches
    (spawned children — the PS_FAULT_PLAN / PS_TRACE_DIR pattern).
    Dumps land as ``blackbox-<role>-<rank>-<pid>.json`` for
    ``cli postmortem <dir>`` to merge."""

    dir: str = ""  # "" = disabled (the identity-pinned no-op path)
    capacity: int = 4096  # event ring bound per process
    # periodic re-dump cadence: what a SIGKILL'd process leaves behind
    # is at most this stale; 0 disables the flusher (trigger dumps only)
    flush_interval_s: float = 1.0
    # watchdog sampling cadence and the no-progress-while-busy window
    # after which a registered source (apply engine, SSP clock, pipeline
    # reader, heartbeat thread) is declared stalled and dumped
    watchdog_interval_s: float = 1.0
    stall_timeout_s: float = 30.0


@dataclass
class TimeseriesConfig:
    """Live cluster time series (utils/timeseries.py): every node keeps a
    bounded ring of timestamped telemetry DELTAS (counter rates + exact
    bucket-wise histogram deltas -> windowed p50/p99), fed from the same
    ``telemetry_snapshot()`` roll the heartbeats piggyback; the
    coordinator retains each node's beat stream in its own ring, which is
    what ``cli top`` and the ``[slo]`` burn-rate engine read."""

    # ring entries retained per node (~30 min of history at the default
    # 5 s heartbeat cadence)
    capacity: int = 360
    # default dashboard window (cli top / the telemetry command's
    # windowed rates + percentiles)
    window_s: float = 60.0
    # OpenMetrics scrape endpoint (/metrics + /healthz, stdlib HTTP):
    # 0 disables; > 0 is the BASE port — the scheduler binds it exactly,
    # server rank r binds base+1+r, worker rank r binds
    # base+1+num_servers+r, so one host's processes never collide. The
    # PS_METRICS_PORT env var arms processes the config never reaches.
    metrics_port: int = 0
    # scrape bind address: the loopback default only serves same-host
    # scrapers; set "0.0.0.0" for an off-host Prometheus (the endpoint
    # is unauthenticated read-only telemetry — bind wide deliberately)
    metrics_host: str = "127.0.0.1"


@dataclass
class ProfileConfig:
    """Continuous sampling profiler (utils/profiler.py): a daemon thread
    samples ``sys._current_frames()`` at ``hz``, folds stacks, and the
    top-N hot stacks ride the heartbeat telemetry piggyback. Disarmed
    (hz=0) it follows the flightrec discipline: the module-level
    ``top_stacks`` is an identity-pinned no-op and no thread exists.
    The ``PS_PROFILE`` env var (a rate in Hz, or 1/true/on for the
    default rate) arms processes the config never reaches."""

    hz: float = 0.0  # sampling rate; 0 = profiler off
    top_n: int = 5  # hot stacks piggybacked per heartbeat
    max_depth: int = 24  # frames kept per folded stack
    # write prof-<name>-<pid>.collapsed (flamegraph/speedscope input) and
    # a Perfetto-loadable .trace.json here at process exit / dump()
    dump_dir: str = ""


@dataclass
class AuditConfig:
    """Live audit plane (ISSUE 14): nodes spool audit-relevant
    flight-recorder events (utils/flightrec.py ``EventSpool``) and ship
    them as sequence-numbered batches on the heartbeat piggyback; the
    coordinator streams them through the shared protocol monitors
    (analysis/monitors.py via utils/auditor.py) — the LIVE incarnation
    of the invariants psmc proves offline (exactly-once pushes, RCU
    version monotonicity, SSP staleness, heal convergence, shed storms).
    Violations fire ``audit.violation`` flight-recorder events, bump
    ``audit_violations`` (the dormant-until-violated ``[slo]`` hook),
    and surface in ``cli top`` and ``cli audit``."""

    enabled: bool = True
    # node-side event spool bound; a full spool drops NEW events and
    # counts them (``audit_spool_dropped``) — the auditor reads the
    # drop watermark and suppresses verdicts over holed windows
    spool_capacity: int = 4096
    # events per drained batch (a beat carries up to 4 batches)
    batch_events: int = 512
    # pairing window: an acked push whose apply.commit has not been
    # seen this many seconds after the ack arrived is a violation
    # (must comfortably exceed the heartbeat interval — the commit
    # rides the SERVER's next beat)
    watermark_s: float = 15.0
    # a heal.begin with no rpc.healed after this long is a violation
    heal_timeout_s: float = 30.0
    # shed-storm detector: >= n sheds within window_s
    shed_storm_n: int = 10
    shed_storm_window_s: float = 1.0
    # recent violations retained for cli audit / cli top panels
    recent: int = 256


@dataclass
class SloConfig:
    """Declarative SLO rules (utils/slo.py), evaluated as multi-window
    burn rates over each node's time-series ring at the coordinator.

    Rule grammar, one string per rule::

        <name> <kind>:<series> <= <threshold> [target <frac>] [burn <x>]

    ``kind`` is ``rate`` (counter delta per second), ``p50`` or ``p99``
    (windowed histogram percentile — milliseconds for latency series,
    raw values for ``.n`` count series). A window's error budget is
    ``1 - target`` (default 0.99); an alert fires when the budget burns
    at >= ``burn``x (default 10) over BOTH the short and the long
    window, once per episode (it re-arms only after both windows
    recover). ``replication_lag_s`` is declared but has no emitter yet —
    it is the reserved health signal for chain replication (ROADMAP
    direction #1); a series with no data never burns."""

    rules: list[str] = field(default_factory=lambda: [
        "push_p99_ms p99:server.push <= 250",
        "shed_rate rate:serve_shed <= 10",
        "stall_count rate:watchdog_stalls <= 0",
        "ssp_blocked_ms rate:ssp_blocked_ms <= 500",
        "apply_queue_depth p99:server.apply_queue.n <= 192",
        "replication_lag_s p99:replication_lag_s <= 1",
        # freshness plane (ISSUE 17): realized data age of client
        # serves (server-measured _age_us echo + local cache dwell) and
        # realized SSP staleness at the gate. Both are dormant until a
        # freshness-armed serve/gate emits the series — the shipped
        # thresholds are the paper's serving-tier defaults (age under a
        # second; lag within the configured bound's usual allowance)
        "pull_age_ms p99:serve.age_s <= 1000",
        "ssp_lag_clocks p99:ssp.lag_clocks.n <= 8",
        # the audit plane's alert hook (ISSUE 14): the coordinator bumps
        # audit_violations in its own ring, so a sustained violation
        # stream pages through the same burn-rate machinery; a clean
        # cluster's rate is exactly 0 and the rule never burns
        "audit_violations rate:audit_violations <= 0 target 0.9 burn 1",
    ])
    short_window_s: float = 60.0
    long_window_s: float = 300.0


@dataclass
class PSConfig:
    """Top-level app config (ref: linear_method.proto LinearMethodConfig)."""

    app: str = "linear_method"
    data: DataConfig = field(default_factory=DataConfig)
    lr: LearningRateConfig = field(default_factory=LearningRateConfig)
    penalty: PenaltyConfig = field(default_factory=PenaltyConfig)
    solver: SolverConfig = field(default_factory=SolverConfig)
    filter: FilterConfig = field(default_factory=FilterConfig)
    graph: GraphConfig = field(default_factory=GraphConfig)
    sketch: SketchConfig = field(default_factory=SketchConfig)
    mf: MFConfig = field(default_factory=MFConfig)
    w2v: W2VConfig = field(default_factory=W2VConfig)
    wd: WDConfig = field(default_factory=WDConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    wire: WireConfig = field(default_factory=WireConfig)
    server: ServerConfig = field(default_factory=ServerConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    fault: FaultConfig = field(default_factory=FaultConfig)
    trace: TraceConfig = field(default_factory=TraceConfig)
    blackbox: BlackboxConfig = field(default_factory=BlackboxConfig)
    timeseries: TimeseriesConfig = field(default_factory=TimeseriesConfig)
    profile: ProfileConfig = field(default_factory=ProfileConfig)
    slo: SloConfig = field(default_factory=SloConfig)
    audit: AuditConfig = field(default_factory=AuditConfig)
    model_output: str = ""
    report_interval: int = 1  # progress print cadence, in reports (ref gflag)
    seed: int = 0


def _from_dict(cls: type, d: dict[str, Any]) -> Any:
    known = {f.name for f in dataclasses.fields(cls)}
    unknown = set(d) - known
    if unknown:
        raise ValueError(
            f"unknown {cls.__name__} field(s) {sorted(unknown)}; known: {sorted(known)}"
        )
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in d:
            continue
        v = d[f.name]
        if f.name in _NESTED:
            if not isinstance(v, dict):
                raise TypeError(
                    f"config section '{f.name}' must be a table/object, got {type(v).__name__}"
                )
            kwargs[f.name] = _from_dict(_NESTED[f.name], v)
        else:
            kwargs[f.name] = v
    return cls(**kwargs)


_NESTED = {
    "data": DataConfig,
    "lr": LearningRateConfig,
    "penalty": PenaltyConfig,
    "solver": SolverConfig,
    "filter": FilterConfig,
    "graph": GraphConfig,
    "sketch": SketchConfig,
    "mf": MFConfig,
    "w2v": W2VConfig,
    "wd": WDConfig,
    "parallel": ParallelConfig,
    "mesh": MeshConfig,
    "wire": WireConfig,
    "server": ServerConfig,
    "serve": ServeConfig,
    "fault": FaultConfig,
    "trace": TraceConfig,
    "blackbox": BlackboxConfig,
    "timeseries": TimeseriesConfig,
    "profile": ProfileConfig,
    "slo": SloConfig,
    "audit": AuditConfig,
}


def toml_module():
    """The tomllib import ladder, shared with pslint's ``[tool.pslint]``
    loader (analysis/core.py): stdlib tomllib (python >= 3.11), the
    tomli upstream, then — last resort on dep-frozen 3.10 images — pip's
    vendored copy; prefer a fragile import to losing .toml support."""
    try:
        import tomllib  # stdlib, python >= 3.11
    except ModuleNotFoundError:
        try:
            import tomli as tomllib  # the stdlib module's upstream
        except ModuleNotFoundError:
            from pip._vendor import tomli as tomllib
    return tomllib


def load_config(path: str | Path) -> PSConfig:
    """Load a PSConfig from a .json or .toml file."""
    p = Path(path)
    if p.suffix == ".toml":
        d = toml_module().loads(p.read_text())
    else:
        d = json.loads(p.read_text())
    return _from_dict(PSConfig, d)


def config_to_dict(cfg: PSConfig) -> dict[str, Any]:
    return dataclasses.asdict(cfg)
