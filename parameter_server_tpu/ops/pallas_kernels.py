"""Pallas TPU kernels for the framework's hot server-update paths.

Three kernels (reference analogs: the server's FTRLEntry update loop —
HOT LOOP #2 of the async-SGD path — and filter/fixing_float.h's
randomized rounding):

- ``ftrl_delta_pallas``: the fused FTRL-proximal delta over gathered
  rows. One VMEM pass computes w(z, n), sigma, and both deltas — no f32
  intermediates spill to HBM between the ~10 elementwise ops.
- ``quantize_stochastic_pallas``: int8/int16 fixed-point quantization
  with hardware-PRNG stochastic rounding (the DCN codec's device path).
- ``ftrl_push_pallas``: the ENTIRE push (gather -> FTRL -> scatter) as
  one kernel with in-place tables — per-tile row DMAs instead of the
  XLA composite's two HBM round trips (see its own layout note below).

All fall back to / are parity-checked against the jnp implementations
off-TPU (CPU tests run interpret mode; TPU runs the kernels — bench.py
compares them and picks winners).

Layout note: tables are (rows, vdim); the two ELEMENTWISE kernels
flatten to (M, 128) lanes and pad the tail, because the VPU wants a
128-wide last dimension and vdim is often 1 (sparse LR) — tiling over
rows alone would waste 127/128 lanes. The fused push kernel is instead
DMA-bound and keeps (tile, vdim) row buffers: its cost is the row
copies, not the VPU math.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_LANES = 128
_SUBLANES = 8
# rows per grid step: VMEM is ~16 MiB scoped; 5 live (TILE_M, 128) f32
# refs at 1024 rows = 2.5 MiB, leaving room for double-buffered pipelining
_TILE_M = 1024


def _pad_to_tiles(x: jax.Array, row_multiple: int = _SUBLANES) -> tuple[jax.Array, int]:
    """Flatten to 1-D and pad so it reshapes to (M, 128) with
    M % row_multiple == 0 (grids tile rows in row_multiple chunks)."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    tile = _LANES * row_multiple
    padded = (n + tile - 1) // tile * tile
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    return flat.reshape(-1, _LANES), n


def _tiled(x: jax.Array) -> tuple[jax.Array, int, int, int]:
    """Pad + reshape to (M, 128) and pick a row tiling: small arrays run
    as one block; large ones pad M to a _TILE_M multiple and grid over
    row tiles (an ungridded call would stage the WHOLE array into VMEM
    and OOM its ~16 MiB scoped limit on real hardware)."""
    row_mult = _TILE_M if x.size > _TILE_M * _LANES else _SUBLANES
    mat, count = _pad_to_tiles(x, row_mult)
    tile_m = min(_TILE_M, mat.shape[0])
    return mat, count, tile_m, mat.shape[0] // tile_m


def _unpad(mat: jax.Array, n: int, shape) -> jax.Array:
    return mat.reshape(-1)[:n].reshape(shape)


def _ftrl_delta_kernel(z_ref, n_ref, g_ref, dz_ref, dn_ref, *, alpha, beta, l1, l2):
    z = z_ref[:]
    n = n_ref[:]
    g = g_ref[:]
    # lazy weight w(z, n)
    shrunk = jnp.sign(z) * jnp.maximum(jnp.abs(z) - l1, 0.0)
    denom = (beta + jnp.sqrt(n)) / alpha + l2
    w = -shrunk / denom
    g2 = g * g
    sigma = (jnp.sqrt(n + g2) - jnp.sqrt(n)) / alpha
    dz_ref[:] = g - sigma * w
    dn_ref[:] = g2


@functools.partial(jax.jit, static_argnames=("alpha", "beta", "l1", "l2"))
def ftrl_delta_pallas(
    z: jax.Array,
    n: jax.Array,
    g: jax.Array,
    *,
    alpha: float,
    beta: float,
    l1: float,
    l2: float,
) -> tuple[jax.Array, jax.Array]:
    """Fused FTRL delta (dz, dn) over row slices of any shape."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    zm, count, tile_m, grid = _tiled(z)
    nm, _, _, _ = _tiled(n)
    gm, _, _, _ = _tiled(g)
    kernel = functools.partial(
        _ftrl_delta_kernel, alpha=alpha, beta=beta, l1=l1, l2=l2
    )
    row_block = pl.BlockSpec(
        (tile_m, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    dz, dn = pl.pallas_call(
        kernel,
        grid=(grid,),
        out_shape=(
            jax.ShapeDtypeStruct(zm.shape, zm.dtype),
            jax.ShapeDtypeStruct(nm.shape, nm.dtype),
        ),
        in_specs=[row_block, row_block, row_block],
        out_specs=(row_block, row_block),
    )(zm, nm, gm)
    return _unpad(dz, count, z.shape), _unpad(dn, count, n.shape)


def _quantize_kernel(seed_ref, params_ref, x_ref, q_ref, *, levels):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # per-grid-step seed, decorrelated across calls: seed+1 must not
    # reproduce this call's tile streams shifted by one (callers pass
    # consecutive per-step seeds)
    pltpu.prng_seed(seed_ref[0] * pl.num_programs(0) + pl.program_id(0))
    lo = params_ref[0]
    scale = params_ref[1]
    t = (x_ref[:] - lo) / scale  # in [0, levels]
    floor = jnp.floor(t)
    frac = t - floor
    bits = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.uint32)
    # uniform in [0, 1) from the top 24 bits (fits in int32, which Mosaic
    # can cast to float32; a direct uint32->float32 cast is unsupported)
    top24 = pltpu.bitcast(bits >> jnp.uint32(8), jnp.int32)
    u = top24.astype(jnp.float32) * (1.0 / (1 << 24))
    q = floor + (u < frac).astype(jnp.float32)
    q_ref[:] = (q - levels // 2).astype(q_ref.dtype)


@functools.partial(jax.jit, static_argnames=("num_bytes",))
def quantize_stochastic_pallas(
    seed: jax.Array, x: jax.Array, num_bytes: int = 1
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Device-side fixed-point encode: (q, lo, scale). Hardware PRNG does
    the unbiased rounding (ref: fixing_float randomized rounding). The
    min/max reduction happens outside the kernel (on the unpadded array,
    fused by XLA); the kernel does the bandwidth-heavy rounding pass."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    levels = (1 << (8 * num_bytes)) - 1
    dtype = jnp.int8 if num_bytes == 1 else jnp.int16
    lo = jnp.min(x).astype(jnp.float32)
    hi = jnp.max(x).astype(jnp.float32)
    scale = jnp.maximum(hi - lo, 1e-30) / levels
    xm, count, tile_m, grid = _tiled(x)
    kernel = functools.partial(_quantize_kernel, levels=levels)
    q = pl.pallas_call(
        kernel,
        grid=(grid,),
        out_shape=jax.ShapeDtypeStruct(xm.shape, dtype),
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((2,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((tile_m, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (tile_m, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
    )(
        jnp.asarray([seed], dtype=jnp.int32),
        jnp.stack([lo, scale]),
        xm,
    )
    return _unpad(q, count, x.shape), lo, scale


# ---------------------------------------------------------------------------
# fused gather -> update -> scatter (the reference's HOT LOOP #2 as ONE
# kernel; SURVEY §2.3 KVMap TPU plan). The XLA composite (kv/store.push)
# is gather + fused-elementwise + scatter-add: the touched rows make two
# HBM round trips (gather read; scatter read-modify-write). This kernel
# makes one — per-tile row DMAs into VMEM, the delta in-register, row
# DMAs back — with the tables aliased in place. Whether the DMA-per-row
# cost beats XLA's native gather/scatter is exactly what bench.py's
# fused_push_* comparisons exist to measure (VERDICT r4 #3: build it and
# let the winner-picks guard decide).
#
# Scope note: the INTEGRATED train step (models/linear.train_step) shares
# its pull gather with the update — its rows are already in registers
# when the delta runs, so its scatter-add costs one read+write per row,
# the same traffic as this kernel. The fused push therefore targets the
# STANDALONE push path — the wire-tier server applying pushes without a
# forward (parallel/multislice), and kv.store API users — not the fused
# single-step trainer, whose headline number it cannot improve
# mechanically.
# ---------------------------------------------------------------------------

_PUSH_TILE = 256  # touched rows per grid step (DMAs in flight per wave)


def _make_push2_kernel(update_rows, tile):
    """Scaffold for fused pushes over TWO-table row state ((z,n) FTRL,
    (w,n) AdaGrad): per-tile row DMAs in, ``update_rows(a, b, g) ->
    (a_new, b_new)`` in-register, row DMAs back. The updater math is the
    only part that varies; the DMA choreography is shared."""

    def kernel(idx_ref, g_ref, a_in, b_in, a_out, b_out, abuf, bbuf, sem):
        from jax import lax
        from jax.experimental.pallas import tpu as pltpu

        del a_in, b_in  # aliased: a_out/b_out ARE the live tables

        def gather(i, _):
            r = idx_ref[i]
            pltpu.make_async_copy(a_out.at[r], abuf.at[i], sem).start()
            pltpu.make_async_copy(b_out.at[r], bbuf.at[i], sem).start()
            return 0

        lax.fori_loop(0, tile, gather, 0)

        def gather_wait(i, _):
            r = idx_ref[i]
            pltpu.make_async_copy(a_out.at[r], abuf.at[i], sem).wait()
            pltpu.make_async_copy(b_out.at[r], bbuf.at[i], sem).wait()
            return 0

        lax.fori_loop(0, tile, gather_wait, 0)

        a_new, b_new = update_rows(abuf[:], bbuf[:], g_ref[:])
        abuf[:] = a_new
        bbuf[:] = b_new

        def scatter(i, _):
            r = idx_ref[i]
            pltpu.make_async_copy(abuf.at[i], a_out.at[r], sem).start()
            pltpu.make_async_copy(bbuf.at[i], b_out.at[r], sem).start()
            return 0

        lax.fori_loop(0, tile, scatter, 0)

        def scatter_wait(i, _):
            r = idx_ref[i]
            pltpu.make_async_copy(abuf.at[i], a_out.at[r], sem).wait()
            pltpu.make_async_copy(bbuf.at[i], b_out.at[r], sem).wait()
            return 0

        lax.fori_loop(0, tile, scatter_wait, 0)

    return kernel


def _ftrl_update_rows(alpha, beta, l1, l2):
    # identical op ORDER to Ftrl.delta + the scatter-add (z + (dz)); the
    # composite may still differ by ULPs where XLA contracts a
    # multiply-add pair into one FMA (e.g. n + g*g)
    def update(z, n, g):
        shrunk = jnp.sign(z) * jnp.maximum(jnp.abs(z) - l1, 0.0)
        w = -shrunk / ((beta + jnp.sqrt(n)) / alpha + l2)
        g2 = g * g
        sigma = (jnp.sqrt(n + g2) - jnp.sqrt(n)) / alpha
        return z + (g - sigma * w), n + g2

    return update


def _adagrad_update_rows(eta, eps, l2):
    # mirrors Adagrad.delta + scatter-add: g' = g + l2*w; dn = g'^2;
    # w += -eta*g'/(sqrt(n+dn)+eps); n += dn
    def update(w, n, g):
        g = g + l2 * w
        dn = g * g
        n_new = n + dn
        return w + (-eta * g / (jnp.sqrt(n_new) + eps)), n_new

    return update


def _push2_pallas(a, b, idx, grad, update_rows):
    """Shared pallas_call plumbing for the fused two-table pushes: pads
    the touched set to a tile multiple (pad slots hit key 0 with zero
    grad), DMAs rows through VMEM, and aliases both tables in place.

    Pad-slot semantics: the kernel row-OVERWRITES where the composite
    scatter-ADDs, so duplicate pad slots agree with kv.store.push only
    when the pad row's update is exactly zero. That holds for FTRL with
    ANY row-0 state (zero grad -> zero delta); for AdaGrad with l2 > 0
    it additionally relies on the framework invariant that the PAD row's
    state IS zero (init zeros it, dumps/updates exclude it, and a zero
    w[0] keeps l2*w[0] zero forever). Callers that break that invariant
    get divergent row-0 garbage in both paths — don't."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    vdim = a.shape[1]
    u = idx.shape[0]
    tile = min(_PUSH_TILE, max(8, u))
    u_pad = (u + tile - 1) // tile * tile
    if u_pad != u:
        idx = jnp.pad(idx, (0, u_pad - u))
        grad = jnp.pad(grad, ((0, u_pad - u), (0, 0)))
    return pl.pallas_call(
        _make_push2_kernel(update_rows, tile),
        grid=(u_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec((tile, vdim), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(a.shape, a.dtype),
            jax.ShapeDtypeStruct(b.shape, b.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((tile, vdim), jnp.float32),
            pltpu.VMEM((tile, vdim), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
        input_output_aliases={2: 0, 3: 1},
    )(idx.astype(jnp.int32), grad, a, b)


@functools.partial(
    jax.jit, static_argnames=("alpha", "beta", "l1", "l2"), donate_argnums=(0,)
)
def ftrl_push_pallas(
    state: dict,
    idx: jax.Array,  # (U,) int32 unique touched keys; pads are idx 0, g 0
    grad: jax.Array,  # (U, vdim) aligned gradient
    *,
    alpha: float,
    beta: float,
    l1: float,
    l2: float,
) -> dict:
    """Fused in-place FTRL push over the touched rows: one HBM round trip
    per row instead of the composite's two. Same contract as
    kv.store.push (unique real keys; duplicate PAD rows carry zero grad,
    so their concurrent same-value row writes are benign)."""
    z2, n2 = _push2_pallas(
        state["z"], state["n"], idx, grad,
        _ftrl_update_rows(alpha, beta, l1, l2),
    )
    return {"z": z2, "n": n2}


@functools.partial(
    jax.jit, static_argnames=("eta", "eps", "l2"), donate_argnums=(0,)
)
def adagrad_push_pallas(
    state: dict,
    idx: jax.Array,
    grad: jax.Array,
    *,
    eta: float,
    eps: float = 1e-8,
    l2: float = 0.0,
) -> dict:
    """Fused in-place AdaGrad push — the embedding-table updater (W&D
    emb, MF factors, word2vec tables), where vdim is 16-64 and each row
    DMA moves a real vector; the most plausible fused-push win."""
    w2, n2 = _push2_pallas(
        state["w"], state["n"], idx, grad, _adagrad_update_rows(eta, eps, l2)
    )
    return {"w": w2, "n": n2}


def tpu_available() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False
