"""Device kernels: CSR segment ops, losses, and (later) Pallas fusions."""

from parameter_server_tpu.ops.sparse import (  # noqa: F401
    csr_grad,
    csr_logits,
    logistic_loss,
)
