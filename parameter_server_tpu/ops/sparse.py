"""Sparse CSR compute as XLA segment ops.

Reference analog: the two hot loops of the async SGD worker
(src/app/linear_method/async_sgd.h): the CSR sparse matvec ``p = X w`` and
its transpose ``g = X^T (sigma(p) - y)``. On TPU both are static-shape
``segment_sum``s over the flattened CSR entry list — XLA lowers these to
sorted-scatter, and padding entries (value 0 -> slot/row 0) vanish
arithmetically instead of via masks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def csr_logits(
    w_u: jax.Array,  # (U,) or (U, 1) weights for the batch's unique keys
    values: jax.Array,  # (NNZ,)
    local_ids: jax.Array,  # (NNZ,) entry -> unique slot
    row_ids: jax.Array,  # (NNZ,) entry -> example row
    num_rows: int,
) -> jax.Array:
    """p[i] = sum_j X[i,j] * w[j] over the batch's CSR entries -> (B,)."""
    w_flat = w_u.reshape(-1)
    contrib = values * jnp.take(w_flat, local_ids)
    return jax.ops.segment_sum(contrib, row_ids, num_segments=num_rows)


def csr_grad(
    err: jax.Array,  # (B,) per-example residual, already masked
    values: jax.Array,
    local_ids: jax.Array,
    row_ids: jax.Array,
    num_unique: int,
) -> jax.Array:
    """g[u] = sum_i X[i,u] * err[i] -> (U, 1), aligned with unique_keys.

    This is the pre-aggregation (segment sum over duplicate keys) that the
    kv push contract requires."""
    contrib = values * jnp.take(err, row_ids)
    g = jax.ops.segment_sum(contrib, local_ids, num_segments=num_unique)
    return g[:, None]


def logistic_loss(
    logits: jax.Array, labels: jax.Array, mask: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Masked summed logloss and the residual (p - y) * mask.

    Ref: logit loss in src/app/linear_method/loss.h. Stable formulation:
    log(1+e^x) - y*x = softplus(x) - y*x."""
    m = mask.astype(logits.dtype)
    loss = jnp.sum(m * (jax.nn.softplus(logits) - labels * logits))
    err = (jax.nn.sigmoid(logits) - labels) * m
    return loss, err
