"""Spec: direction #1's chain-replication failover, stated as checked
transitions BEFORE any production code exists (ROADMAP #1: live
replication of the apply stream to a chain successor, failover that
promotes the successor and re-points clients mid-window).

The model is the protocol the replication PR must implement:

- a PRIMARY applies client pushes exactly as today (durable ledger,
  cid/seq dedup — the PR-1/PR-4 machinery the model reuses), and
  REPLICATES each applied (seq, delta) to its SUCCESSOR as an ordered
  apply stream;
- the ack to the client is emitted only once the successor acked the
  stream entry (chain discipline: an acked push is on every chain
  member), unless the ``ack-before-replicate`` bug says otherwise;
- the successor applies stream entries in order, dedup'd by the same
  (cid, seq) identity — replay-idempotence is what makes promotion
  safe;
- the primary may CRASH mid-window; the coordinator PROMOTES the
  successor (its replayed apply stream is the new authoritative
  state) and RE-POINTS the client, whose reconnect-resend machinery
  (PR 1) resends every unacked push to the new head — where the
  replicated ledger dedups anything that already rode the stream.

Invariant (every state): no node ever applies one push twice, and an
acked push is applied exactly once on the CURRENT head (acks never
outrun the chain). Liveness (quiescence): every push ends acked and
applied exactly once on the serving head — zero-loss failover.

Seeded bugs (``BUGS``):

    ack-before-replicate  the primary acks on local apply and
                          replicates asynchronously — a crash between
                          ack and stream delivery promotes a successor
                          that never saw the push: the ack outruns the
                          chain and the push is LOST (invariant names
                          the acked-but-unapplied seq at promotion)
    promote-no-dedup      the promoted successor forgets the stream's
                          (cid, seq) identities — the client's
                          re-pointed resend of an unacked-but-
                          replicated push applies TWICE on the new head
    replicate-unordered   the stream applies out of order — the
                          successor's state diverges from the order the
                          primary ledgered (flagged as a stream-order
                          violation; chain replication requires the
                          successor replay the head's serialization)

ASSUMPTIONS (diffed by analysis/conformance.py): the dedup identity
and the durable ledger this model leans on exist in the code exactly
as the exactly-once spec pins them (same derived table — the failover
model composes on those invariants, it does not restate them).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Hashable

from parameter_server_tpu.analysis.model import Spec

BUGS = ("ack-before-replicate", "promote-no-dedup", "replicate-unordered")

#: failover composes on the exactly-once machinery; its conformance
#: table is the same ledger/dedup derivation (see exactly_once)
ASSUMPTIONS = {
    "ledger_record_under_apply_lock": True,
    "ledger_checked_before_apply": True,
}


@dataclass(frozen=True)
class _S:
    acked: tuple[bool, ...]
    p_applied: tuple[int, ...]  # primary apply count per seq
    s_applied: tuple[int, ...]  # successor apply count per seq
    p_ledger: tuple[bool, ...]
    s_ledger: tuple[bool, ...]  # successor's replicated dedup identity
    in_req: tuple[int, ...]  # client frames in flight to current head
    in_ack: tuple[int, ...]
    stream: tuple[int, ...]  # replication stream in flight (seqs, FIFO)
    stream_acked: tuple[bool, ...]  # successor acked this seq's entry
    sent: int
    promoted: bool  # successor is the head; primary is gone
    crashed: bool
    order_broke: bool  # stream applied out of order (bug variant)


class FailoverSpec(Spec):
    name = "failover"

    def __init__(
        self,
        pushes: int = 2,
        window: int = 2,
        bug: str | None = None,
    ):
        if bug is not None and bug not in BUGS:
            raise ValueError(f"unknown bug {bug!r}; known: {BUGS}")
        self.pushes = pushes
        self.window = window
        self.bug = bug

    def init_states(self) -> list[Hashable]:
        n = self.pushes
        z, f = (0,) * n, (False,) * n
        return [_S(f, z, z, f, f, z, z, (), f, 0, False, False, False)]

    def _t(self, t: tuple, i: int, v) -> tuple:
        return t[:i] + (v,) + t[i + 1:]

    def actions(self, s: _S) -> list[tuple[str, Hashable]]:
        out: list[tuple[str, Hashable]] = []
        n = self.pushes
        unacked = sum(1 for i in range(s.sent) if not s.acked[i])
        if s.sent < n and unacked < self.window:
            out.append((
                f"client: send push #{s.sent}",
                replace(s, in_req=self._t(s.in_req, s.sent, 1),
                        sent=s.sent + 1),
            ))
        for i in range(s.sent):
            if (
                not s.acked[i]
                and s.in_req[i] == 0
                and s.in_ack[i] == 0
                and i not in s.stream
            ):
                # reconnect-resend (to whichever node is the head now)
                out.append((
                    f"client: resend push #{i} to the head",
                    replace(s, in_req=self._t(s.in_req, i, 1)),
                ))
            if s.in_req[i] > 0:
                out.append((
                    f"net: drop push #{i}",
                    replace(s, in_req=self._t(s.in_req, i, 0)),
                ))
                # a frame only REACHES a live head: between the crash
                # and the promotion there is no head — frames to the
                # dead primary can only die (drop above), exactly what
                # a dead connection does to them
                if not s.crashed or s.promoted:
                    out.append((
                        f"head: recv push #{i}", self._serve(s, i),
                    ))
            if s.in_ack[i] > 0:
                out.append((
                    f"net: drop ack #{i}",
                    replace(s, in_ack=self._t(s.in_ack, i, 0)),
                ))
                out.append((
                    f"client: recv ack #{i}",
                    replace(s, in_ack=self._t(s.in_ack, i, 0),
                            acked=self._t(s.acked, i, True)),
                ))
        if s.stream and not s.promoted:
            # successor consumes the replication stream. In order —
            # unless the replicate-unordered bug lets a later entry
            # overtake the head of the stream.
            idxs = (
                range(len(s.stream))
                if self.bug == "replicate-unordered"
                else range(1)
            )
            for j in idxs:
                out.append((
                    f"successor: apply stream entry seq #{s.stream[j]}",
                    self._stream_apply(s, j),
                ))
        if not s.crashed and not s.promoted:
            # the crash wipes the primary AND every frame in flight to
            # it (connection death); the replication stream dies too —
            # only entries the successor already applied survive
            out.append((
                "chaos: primary crashes mid-window",
                replace(s, crashed=True, in_req=(0,) * n,
                        in_ack=(0,) * n, stream=()),
            ))
        if s.crashed and not s.promoted:
            ns = s
            if self.bug == "promote-no-dedup":
                ns = replace(ns, s_ledger=(False,) * n)
            # promotion also buries whatever replication stream the dead
            # primary still had in flight: entries the successor never
            # applied are gone (the crash transition wipes it too —
            # belt and braces so the new head can never consume a dead
            # node's stream)
            out.append((
                "coordinator: promote successor, re-point client",
                replace(ns, promoted=True, stream=()),
            ))
        return out

    def _serve(self, s: _S, i: int) -> _S:
        """The current head processes one frame of push i."""
        s = replace(s, in_req=self._t(s.in_req, i, 0))
        if not s.promoted:
            if s.p_ledger[i]:
                # dedup replay: ack only if the chain discipline is
                # satisfied for this seq (the stream entry was acked) —
                # otherwise the reply stays withheld like the original
                if s.stream_acked[i] or self.bug == "ack-before-replicate":
                    return replace(s, in_ack=self._t(s.in_ack, i, 1))
                return s
            s = replace(
                s,
                p_applied=self._t(s.p_applied, i, s.p_applied[i] + 1),
                p_ledger=self._t(s.p_ledger, i, True),
                stream=s.stream + (i,),
            )
            if self.bug == "ack-before-replicate":
                s = replace(s, in_ack=self._t(s.in_ack, i, 1))
            return s
        # promoted successor is the head: same protocol, its ledger
        if s.s_ledger[i]:
            return replace(s, in_ack=self._t(s.in_ack, i, 1))
        return replace(
            s,
            s_applied=self._t(s.s_applied, i, s.s_applied[i] + 1),
            s_ledger=self._t(s.s_ledger, i, True),
            in_ack=self._t(s.in_ack, i, 1),
        )

    def _stream_apply(self, s: _S, j: int) -> _S:
        i = s.stream[j]
        order_broke = s.order_broke or j != 0
        ns = replace(
            s, stream=s.stream[:j] + s.stream[j + 1:],
            order_broke=order_broke,
        )
        if ns.s_ledger[i]:
            return replace(
                ns, stream_acked=self._t(ns.stream_acked, i, True),
            )
        return replace(
            ns,
            s_applied=self._t(ns.s_applied, i, ns.s_applied[i] + 1),
            s_ledger=self._t(ns.s_ledger, i, True),
            stream_acked=self._t(ns.stream_acked, i, True),
        )

    # -- properties --------------------------------------------------------

    def invariant(self, s: _S) -> str | None:
        for i in range(self.pushes):
            if s.p_applied[i] > 1 or s.s_applied[i] > 1:
                node = "primary" if s.p_applied[i] > 1 else "successor"
                return (
                    f"push #{i} applied {max(s.p_applied[i], s.s_applied[i])} "
                    f"times on the {node} — replay dedup broken on the "
                    "chain (promotion forgot the stream's identities?)"
                )
            if s.promoted and s.acked[i] and s.s_applied[i] == 0:
                return (
                    f"push #{i} was acked but the promoted successor "
                    "never applied it — the ack outran the replication "
                    "stream and the push is lost (chain discipline: "
                    "ack only after the successor holds the entry)"
                )
        if s.order_broke:
            return (
                "the successor applied the replication stream out of "
                "order — its state diverges from the serialization the "
                "primary ledgered"
            )
        return None

    def liveness(self, s: _S) -> str | None:
        head_applied = s.s_applied if s.promoted else s.p_applied
        bad = [
            i for i in range(self.pushes)
            if not (s.acked[i] and head_applied[i] == 1)
        ]
        if bad:
            return (
                f"quiescent with push(es) {bad} not acked+applied on "
                "the serving head — failover lost or wedged them"
            )
        return None


def make(bug: str | None = None, **bounds) -> FailoverSpec:
    return FailoverSpec(bug=bug, **bounds)


def tier1() -> FailoverSpec:
    return FailoverSpec(pushes=2, window=2)
