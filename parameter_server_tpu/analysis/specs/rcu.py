"""Spec: the versioned RCU publish/read protocol of
``ShardServer._pub`` — one reference swap publishes the (state,
version) pair, readers capture the WHOLE pair in one load, versions are
strictly monotonic within a server life, and a per-life nonce keeps a
version cached from a PREVIOUS life (whose tail applies a checkpoint
restart rolled back) from ever falsely validating an ``if_newer``
revalidation.

The model's ground truth for "which rows" is ``(life, applies)``: the
content a snapshot holds is determined by which life produced it and
how many applies it folded — a restart that rolls back and re-applies
produces DIFFERENT content at the same per-life counter (re-sent
pushes coalesce into different batches), which is exactly why a bare
counter can falsely validate. A reader may capture the published pair,
cache it, and later revalidate: version equality serves the CACHED
rows (the serving plane's ``not_modified`` path).

Invariants: (a) every publish strictly increases the version within
its life; (b) a version-equality revalidation serves rows identical to
the server's current snapshot (the false-validate check — this is what
tears and nonce-less rollbacks break).

Seeded bugs (``BUGS``):

    torn-publish   version and state swap in two steps (version
                   first) — a capture between them pairs OLD rows with
                   the NEW version; once the state lands, revalidation
                   matches versions and serves the stale rows
    no-nonce       versions restart from the checkpointed counter in a
                   new life without a namespace — a cached pre-crash
                   version collides with a post-restart version whose
                   rows differ (the rolled-back tail re-applied in
                   different batches)
    no-bump        a publish path skips the version bump — two
                   different snapshots share a version (monotonicity)

ASSUMPTIONS (diffed by analysis/conformance.py): the only method that
stores ``self._pub`` outside ``__init__`` is the ``state`` setter (the
single publish site), and that setter bumps the version by one.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Hashable

from parameter_server_tpu.analysis.model import Spec

BUGS = ("torn-publish", "no-nonce", "no-bump")

ASSUMPTIONS = {
    # the only _pub store sites outside __init__: the property setter
    "publish_sites": frozenset({"state"}),
    "publish_bumps_version": True,
}

Rows = tuple[int, int]  # (life, applies): the content identity
Ver = tuple[int, int]  # (nonce, counter); nonce 0 under no-nonce


@dataclass(frozen=True)
class _S:
    life: int
    counter: int  # per-life publish counter (the version's low bits)
    applies: int  # applies folded this life (content ground truth)
    pub_rows: Rows  # published state slot
    pub_ver: Ver  # published version slot
    torn: bool  # between the two stores of a torn publish
    pend_rows: Rows | None  # the state the torn publish will land
    applies_left: int
    restarts_left: int
    reads_left: int  # bounded captures: quiescence must be reachable
    ckpt: tuple[int, int] | None  # (counter, applies) checkpointed
    cache: tuple[Ver, Rows] | None  # reader's cached (version, rows)
    stale_served: bool  # a matching revalidation served foreign rows
    nonmono: bool  # a publish failed to increase within its life


class RcuSpec(Spec):
    name = "rcu"

    def __init__(
        self,
        applies: int = 3,
        restarts: int = 1,
        reads: int = 3,
        bug: str | None = None,
    ):
        if bug is not None and bug not in BUGS:
            raise ValueError(f"unknown bug {bug!r}; known: {BUGS}")
        self.applies = applies
        self.restarts = restarts
        # reader capture budget: without one a reader action is enabled
        # in EVERY state, no quiescent state ever exists, and the
        # liveness hook is vacuously unreachable
        self.reads = reads
        self.bug = bug

    def _ver(self, life: int, counter: int) -> Ver:
        # the per-life nonce: real versions namespace a 40-bit counter
        # by fresh random high bits per life; the model uses the life id
        # itself. The no-nonce bug drops the namespace.
        return (0, counter) if self.bug == "no-nonce" else (life, counter)

    def init_states(self) -> list[Hashable]:
        return [_S(
            life=1, counter=1, applies=0, pub_rows=(1, 0),
            pub_ver=self._ver(1, 1), torn=False, pend_rows=None,
            applies_left=self.applies, restarts_left=self.restarts,
            reads_left=self.reads, ckpt=None, cache=None,
            stale_served=False, nonmono=False,
        )]

    def actions(self, s: _S) -> list[tuple[str, Hashable]]:
        out: list[tuple[str, Hashable]] = []
        if s.torn:
            # second store of the torn publish: the state lands
            out.append((
                "writer: publish step 2 (store state)",
                replace(s, torn=False, pub_rows=s.pend_rows,
                        pend_rows=None),
            ))
        elif s.applies_left > 0:
            nc = s.counter if self.bug == "no-bump" else s.counter + 1
            na = s.applies + 1
            nv = self._ver(s.life, nc)
            mono_broke = s.nonmono or nc <= s.counter
            base = replace(
                s, applies_left=s.applies_left - 1, applies=na,
                counter=nc, nonmono=mono_broke,
            )
            if self.bug == "torn-publish":
                # version stored first, state later — the window where
                # a capture pairs OLD rows with the NEW version (what
                # the one-tuple swap exists to make impossible)
                out.append((
                    "writer: publish step 1 (store version)",
                    replace(base, pub_ver=nv, torn=True,
                            pend_rows=(s.life, na)),
                ))
            else:
                out.append((
                    "writer: publish (one tuple swap)",
                    replace(base, pub_rows=(s.life, na), pub_ver=nv),
                ))
        if s.ckpt is None and not s.torn and s.restarts_left > 0:
            out.append((
                "server: checkpoint (state + version counter)",
                replace(s, ckpt=(s.counter, s.applies)),
            ))
        if s.cache is None:
            if s.reads_left > 0:
                out.append((
                    "reader: capture published pair + cache",
                    replace(s, cache=(s.pub_ver, s.pub_rows),
                            reads_left=s.reads_left - 1),
                ))
        else:
            ver, rows = s.cache
            if ver == s.pub_ver:
                out.append((
                    "reader: revalidate if_newer -> not_modified "
                    "(serve cached rows)",
                    replace(s, cache=None,
                            stale_served=s.stale_served
                            or rows != s.pub_rows),
                ))
            else:
                out.append((
                    "reader: revalidate if_newer -> version moved, "
                    "refresh rows",
                    replace(s, cache=None),
                ))
        if s.restarts_left > 0 and s.ckpt is not None and not s.torn:
            ck_counter, ck_applies = s.ckpt
            nl = s.life + 1
            rolled_back = s.applies - ck_applies
            out.append((
                "server: crash + restart from checkpoint (tail applies "
                "rolled back; clients will resend them)",
                replace(
                    s, life=nl, counter=ck_counter, applies=ck_applies,
                    pub_rows=(nl, ck_applies),
                    pub_ver=self._ver(nl, ck_counter),
                    restarts_left=s.restarts_left - 1, ckpt=None,
                    applies_left=s.applies_left + rolled_back,
                ),
            ))
        return out

    def invariant(self, s: _S) -> str | None:
        if s.stale_served:
            return (
                "a version-equality revalidation served rows that are "
                "not the current snapshot — a cached version falsely "
                "validated (torn publish, or a rollback re-used a "
                "version without a life nonce)"
            )
        if s.nonmono:
            return (
                "a publish did not increase the version within its "
                "life — two different snapshots share a version"
            )
        return None

    def liveness(self, s: _S) -> str | None:
        if s.applies_left > 0 or s.torn:
            return "writer wedged with applies outstanding"
        return None


def make(bug: str | None = None, **bounds) -> RcuSpec:
    return RcuSpec(bug=bug, **bounds)


def tier1() -> RcuSpec:
    return RcuSpec(applies=3, restarts=1, reads=3)
