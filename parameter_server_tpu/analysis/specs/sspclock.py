"""Spec: the SSP clock's bounded-staleness contract under worker
death, coordinator retirement and workload reassignment
(``parallel/ssp.py`` + the PR-1 recovery sweep).

``workers`` logical workers each run ``steps`` steps. A worker may
issue step t only when ``min(finished)`` over every clock entry is at
least ``t - max_delay - 1`` (``SSPClock.wait``'s gate: with tau=0 a
worker is at most one step ahead of the slowest). Finishing advances
its entry. A worker may die mid-run (``deaths`` budget); the
coordinator's sweep RETIRES the dead worker's clock entry by finishing
it at the RETIRED sentinel (so it stops binding the min) and REASSIGNS
its remaining steps to the laggiest live worker (the workload-pool
half of the sweep).

Invariant (every state): no issued step ever ran more than
``max_delay + 1`` ahead of the slowest clock entry at issue time — the
paper's bounded-delay consistency, stated on the model. Liveness
(quiescent states): every live worker finishes its (original plus
reassigned) steps and every dead worker is swept — the gate can never
wedge live workers forever.

Seeded bugs (``BUGS``):

    no-retire       the sweep reassigns work but never retires the dead
                    worker's clock entry — the frozen entry stays in
                    the min and every live worker parks on the gate
                    within max_delay+1 steps: a quiescent state with
                    work outstanding (the deadlock retire prevents)
    retire-as-zero  retirement writes 0 instead of the RETIRED
                    sentinel — the entry re-enters the min at zero and
                    pins it there; everyone wedges at step max_delay+1
    gate-own-clock  the gate consults the worker's OWN entry instead of
                    the cluster min — it never blocks, and the
                    staleness invariant fires as soon as it outruns the
                    slowest worker by more than the bound

ASSUMPTIONS (diffed by analysis/conformance.py): ``SSPClock.retire``
delegates to ``finish`` with the RETIRED sentinel (retirement rides the
same notify path as progress), and ``wait`` recomputes the min inside
its gate predicate (no cached min).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Hashable

from parameter_server_tpu.analysis.model import Spec

BUGS = ("no-retire", "retire-as-zero", "gate-own-clock")

ASSUMPTIONS = {
    "retire_delegates_to_finish": True,
}

_RETIRED = 1 << 10  # model-scale sentinel (the code uses 1 << 60)


@dataclass(frozen=True)
class _S:
    finished: tuple[int, ...]  # per-worker highest finished step
    alive: tuple[bool, ...]
    swept: tuple[bool, ...]  # coordinator sweep ran for this worker
    todo: tuple[int, ...]  # steps this worker still owes
    deaths_left: int
    overrun: bool  # a step issued beyond the staleness bound


class SspSpec(Spec):
    name = "ssp"

    def __init__(
        self,
        workers: int = 2,
        steps: int = 3,
        max_delay: int = 1,
        deaths: int = 1,
        bug: str | None = None,
    ):
        if bug is not None and bug not in BUGS:
            raise ValueError(f"unknown bug {bug!r}; known: {BUGS}")
        self.workers = workers
        self.steps = steps
        self.max_delay = max_delay
        self.deaths = deaths
        self.bug = bug

    def init_states(self) -> list[Hashable]:
        n = self.workers
        return [_S(
            finished=(-1,) * n, alive=(True,) * n, swept=(False,) * n,
            todo=(self.steps,) * n, deaths_left=self.deaths,
            overrun=False,
        )]

    def actions(self, s: _S) -> list[tuple[str, Hashable]]:
        out: list[tuple[str, Hashable]] = []
        # the code's min: over EVERY entry — retirement works by writing
        # a sentinel too large to bind, not by exclusion
        true_min = min(s.finished)
        for w in range(self.workers):
            if not s.alive[w] or s.todo[w] <= 0:
                continue
            t = s.finished[w] + 1
            gate_min = (
                s.finished[w] if self.bug == "gate-own-clock" else true_min
            )
            if gate_min >= t - self.max_delay - 1:
                # issue + run + finish as one transition: the gate is
                # the only synchronization the clock contract speaks to
                overrun = s.overrun or (
                    t - true_min > self.max_delay + 1
                )
                nf = s.finished[:w] + (t,) + s.finished[w + 1:]
                nt = s.todo[:w] + (s.todo[w] - 1,) + s.todo[w + 1:]
                out.append((
                    f"worker {w}: step {t} (gate min={gate_min})",
                    replace(s, finished=nf, todo=nt, overrun=overrun),
                ))
        if s.deaths_left > 0:
            for w in range(self.workers):
                if s.alive[w] and s.todo[w] > 0:
                    na = s.alive[:w] + (False,) + s.alive[w + 1:]
                    out.append((
                        f"chaos: worker {w} dies mid-window",
                        replace(s, alive=na,
                                deaths_left=s.deaths_left - 1),
                    ))
        for w in range(self.workers):
            if s.alive[w] or s.swept[w]:
                continue
            # coordinator sweep (one-shot per death): retire the clock
            # entry + reassign the remaining steps to the laggiest heir
            if self.bug == "no-retire":
                nf = s.finished  # the frozen entry keeps binding
            elif self.bug == "retire-as-zero":
                nf = s.finished[:w] + (0,) + s.finished[w + 1:]
            else:
                nf = s.finished[:w] + (_RETIRED,) + s.finished[w + 1:]
            nsw = s.swept[:w] + (True,) + s.swept[w + 1:]
            nt = list(s.todo)
            moved = nt[w]
            nt[w] = 0
            heirs = [
                x for x in range(self.workers)
                if x != w and s.alive[x]
            ]
            label = f"coordinator: retire worker {w}"
            if heirs and moved > 0:
                heir = min(heirs, key=lambda x: (s.finished[x], x))
                nt[heir] += moved
                label += f" + reassign {moved} step(s) to worker {heir}"
            out.append((
                label,
                replace(s, finished=nf, swept=nsw, todo=tuple(nt)),
            ))
        return out

    def invariant(self, s: _S) -> str | None:
        if s.overrun:
            return (
                "a worker issued a step more than max_delay+1 ahead of "
                "the slowest clock entry — bounded staleness broken "
                "(the gate consulted the wrong clock)"
            )
        return None

    def liveness(self, s: _S) -> str | None:
        stuck = [
            w for w in range(self.workers)
            if s.alive[w] and s.todo[w] > 0
        ]
        if stuck:
            return (
                f"live worker(s) {stuck} parked on the SSP gate forever "
                "with steps outstanding — a dead worker's clock entry "
                "still binds the min (retire/reassign failed)"
            )
        unswept = [
            w for w in range(self.workers)
            if not s.alive[w] and not s.swept[w]
        ]
        if unswept and any(t > 0 for t in s.todo):
            return (
                f"dead worker(s) {unswept} never swept — their steps "
                "are lost"
            )
        return None


def make(bug: str | None = None, **bounds) -> SspSpec:
    return SspSpec(bug=bug, **bounds)


def tier1() -> SspSpec:
    return SspSpec(workers=2, steps=3, max_delay=1, deaths=1)
