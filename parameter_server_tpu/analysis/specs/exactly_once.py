"""Spec: exactly-once pushes over a lossy, duplicating wire with server
crash/restart — the PR-1/PR-4 composition (seq-numbered
reconnect-resend, per-client reply cache, durable applied-push ledger)
as an executable model.

One client pipelines ``pushes`` logical pushes through a window of
``window`` unacked calls. The network may drop or duplicate any
in-flight frame (``dups`` duplication budget per push — bounded message
counts). The server applies a push, records it in the DURABLE ledger
and the VOLATILE reply cache, and emits an ack; ``crashes`` restarts
wipe the reply cache and every in-flight frame (the connection dies
with the process) but not the ledger. The client resends any unacked
push forever (reconnect-resend), so the same logical push can reach the
server arbitrarily many times — dedup is the server's job.

Invariant (checked at every state): an acked push has been applied
EXACTLY once, and no push is ever applied twice. Liveness (at
quiescence, under fairness): every push ends acked and applied.

Seeded bugs the checker must catch (``BUGS``):

    volatile-dedup   dedup consults only the reply cache — a crash
                     between apply and ack forgets the apply, and the
                     client's resend applies it again
    no-dedup         dedup dropped entirely — a duplicated frame
                     applies twice even without a crash
    ack-early        the ack is emitted BEFORE the ledger record — a
                     crash in between acks a push the restarted server
                     will re-apply on resend... and the reply-cache
                     model can't save it (minimal trace shows why)

ASSUMPTIONS (diffed against the code by analysis/conformance.py):
the push-serving server exempts exactly {pull, dump, stats} from the
reply cache (push replies must ride it), owns a durable ledger whose
record call always runs under the apply lock, and consults that ledger
before applying (``_applied_push`` read reaches every apply path).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Hashable

from parameter_server_tpu.analysis.model import Spec

BUGS = ("volatile-dedup", "no-dedup", "ack-early")

#: the facts about parallel/multislice.py this model encodes;
#: analysis/conformance.py derives the code-side table and diffs
ASSUMPTIONS = {
    "idempotent_cmds": frozenset({"pull", "dump", "stats"}),
    "push_rides_reply_cache": True,
    "ledger_record_under_apply_lock": True,
    "ledger_checked_before_apply": True,
}


@dataclass(frozen=True)
class _S:
    """One global state. Per-push tuples are indexed by seq."""

    acked: tuple[bool, ...]
    applied: tuple[int, ...]  # apply count per seq (the invariant's fact)
    ledger: tuple[bool, ...]  # durable: survives restart
    rcache: tuple[bool, ...]  # volatile: dies with the process
    in_push: tuple[int, ...]  # in-flight request frames per seq
    in_ack: tuple[int, ...]  # in-flight ack frames per seq
    sent: int  # pushes issued so far (window head)
    crashes: int  # restart budget left
    dups: tuple[int, ...]  # duplication budget left per seq

    def bump(self, f: str, i: int, d: int = 1) -> "_S":
        t = getattr(self, f)
        return replace(self, **{f: t[:i] + (t[i] + d,) + t[i + 1:]})

    def set(self, f: str, i: int, v) -> "_S":
        t = getattr(self, f)
        return replace(self, **{f: t[:i] + (v,) + t[i + 1:]})


class ExactlyOnce(Spec):
    name = "exactly-once"

    def __init__(
        self,
        pushes: int = 3,
        window: int = 2,
        crashes: int = 1,
        dups: int = 1,
        bug: str | None = None,
    ):
        if bug is not None and bug not in BUGS:
            raise ValueError(f"unknown bug {bug!r}; known: {BUGS}")
        self.pushes = pushes
        self.window = window
        self.crashes = crashes
        self.dups = dups
        self.bug = bug

    def init_states(self) -> list[Hashable]:
        n = self.pushes
        z = (0,) * n
        f = (False,) * n
        return [_S(f, z, f, f, z, z, 0, self.crashes, (self.dups,) * n)]

    # -- transitions -------------------------------------------------------

    def actions(self, s: _S) -> list[tuple[str, Hashable]]:
        out: list[tuple[str, Hashable]] = []
        n = self.pushes
        # client: issue the next push while the unacked window has room
        unacked = sum(
            1 for i in range(s.sent) if not s.acked[i]
        )
        if s.sent < n and unacked < self.window:
            out.append((
                f"client: send push #{s.sent}",
                replace(s.bump("in_push", s.sent), sent=s.sent + 1),
            ))
        for i in range(s.sent):
            # client: resend an unacked push with nothing of it in
            # flight either way (reconnect-resend after a timeout long
            # enough that an in-flight ack would have landed or died —
            # the abstraction that keeps the frame multiset bounded)
            if not s.acked[i] and s.in_push[i] == 0 and s.in_ack[i] == 0:
                out.append((
                    f"client: resend push #{i}", s.bump("in_push", i),
                ))
            if s.in_push[i] > 0:
                # network: duplicate (bounded) or drop a request frame
                if s.dups[i] > 0:
                    out.append((
                        f"net: duplicate push #{i}",
                        s.bump("in_push", i).bump("dups", i, -1),
                    ))
                out.append((
                    f"net: drop push #{i}", s.bump("in_push", i, -1),
                ))
                # server: receive one frame
                out.append((
                    f"server: recv push #{i}", self._serve(s, i),
                ))
            if s.in_ack[i] > 0:
                out.append((
                    f"net: drop ack #{i}", s.bump("in_ack", i, -1),
                ))
                out.append((
                    f"client: recv ack #{i}",
                    s.bump("in_ack", i, -1).set("acked", i, True),
                ))
        for i in range(s.sent):
            # ack-early residue: a push acked + reply-cached but not yet
            # ledgered (only the ack-early bug creates this state — the
            # correct protocol records the ledger in the same atomic
            # apply step). The commit can still land... unless the
            # crash beats it, which is the whole bug.
            if s.rcache[i] and not s.ledger[i]:
                out.append((
                    f"server: ledger-commit push #{i} (late)",
                    s.set("ledger", i, True),
                ))
        if s.crashes > 0:
            # server restart: reply cache and every in-flight frame die
            # with the process; the ledger is durable
            out.append((
                "server: crash + restart",
                replace(
                    s,
                    rcache=(False,) * n,
                    in_push=(0,) * n,
                    in_ack=(0,) * n,
                    crashes=s.crashes - 1,
                ),
            ))
        return out

    def _serve(self, s: _S, i: int) -> _S:
        """Server processes one frame of push i: dedup, apply, ledger,
        reply-cache, ack — with the configured bug knob applied."""
        s = s.bump("in_push", i, -1)
        if self.bug == "no-dedup":
            seen = False
        elif self.bug == "volatile-dedup":
            seen = s.rcache[i]
        elif self.bug == "ack-early":
            # dedup machinery intact (ledger AND reply cache consulted)
            # — the bug is purely the ack/ledger ORDER, so plain
            # duplicates are still deduped and only the crash window
            # between ack and ledger-commit exposes it
            seen = s.ledger[i] or s.rcache[i]
        else:
            seen = s.ledger[i]  # the durable dedup (correct protocol)
        if seen:
            # replay: answer from the dedup machinery without re-applying
            return s.bump("in_ack", i)
        if self.bug == "ack-early":
            # ack + apply + reply-cache now; the DURABLE ledger record
            # is a separate later transition (the 'ledger-commit (late)'
            # action) — a crash in between forgets the apply and the
            # client's resend applies it again
            s = s.bump("in_ack", i)
            s = s.bump("applied", i)
            return s.set("rcache", i, True)
        s = s.bump("applied", i)
        s = s.set("ledger", i, True).set("rcache", i, True)
        return s.bump("in_ack", i)

    # -- properties --------------------------------------------------------

    def invariant(self, s: _S) -> str | None:
        for i in range(self.pushes):
            if s.applied[i] > 1:
                return (
                    f"push #{i} applied {s.applied[i]} times — "
                    "exactly-once broken (duplicate delivery or a "
                    "restart forgot the apply)"
                )
            if s.acked[i] and s.applied[i] != 1:
                return (
                    f"push #{i} acked but applied {s.applied[i]} "
                    "times — 'acked => applied exactly once' broken"
                )
        return None

    def liveness(self, s: _S) -> str | None:
        bad = [
            i
            for i in range(self.pushes)
            if not (s.acked[i] and s.applied[i] == 1)
        ]
        if bad:
            return (
                f"quiescent with push(es) {bad} not acked+applied — "
                "the resend/dedup loop cannot finish the window"
            )
        return None


def make(bug: str | None = None, **bounds) -> ExactlyOnce:
    return ExactlyOnce(bug=bug, **bounds)


def tier1() -> ExactlyOnce:
    """The CI-bounded instance: small enough to exhaust in well under a
    second, big enough that every protocol ingredient (window, resend,
    duplicate, crash) is exercised."""
    return ExactlyOnce(pushes=3, window=2, crashes=1, dups=1)
