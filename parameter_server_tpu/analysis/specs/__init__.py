"""Spec inventory for psmc (analysis/model.py): small executable models
of the repo's core protocols, each with (a) a correct configuration the
checker must verify clean at tier-1 bounds, (b) seeded-bug knobs the
checker must CATCH (mutation coverage for the checker itself), and
(c) ``ASSUMPTIONS`` — the facts about the real code the model's
correctness rests on, diffed against AST-derived tables by
``analysis/conformance.py`` so spec and code cannot drift silently.

    exactly-once   pipelined client window x reconnect-resend x reply
                   cache x durable ledger x server restart; invariant:
                   acked => applied exactly once
    rcu            versioned RCU publish/read: per-life monotonic
                   versions, no torn (state, version) pair observable,
                   per-life nonce so a rolled-back restart can never
                   falsely validate a cached version
    ssp            SSP clock bounded staleness under worker death,
                   retire and reassignment; liveness: the gate never
                   wedges live workers
    failover       direction #1's chain-replication failover, stated as
                   checked transitions BEFORE any production code:
                   primary dies mid-window, successor promotes from the
                   replayed apply stream, clients re-point and resend

Each module exports ``make(bug=None, **bounds) -> Spec``, ``BUGS``
(the seeded-bug knob names) and ``ASSUMPTIONS``; ``tier1()`` returns
the bounded instance ``cli check`` verifies in CI.
"""

from __future__ import annotations

from parameter_server_tpu.analysis.specs import (
    exactly_once,
    failover,
    rcu,
    sspclock,
)

#: name -> spec module (make/tier1/BUGS/ASSUMPTIONS); the registry
#: cli check, the model-invariants checker and the tests all iterate
SPECS = {
    "exactly-once": exactly_once,
    "rcu": rcu,
    "ssp": sspclock,
    "failover": failover,
}
