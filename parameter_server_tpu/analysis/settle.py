"""Checker ``settle-exactly-once``: DeferredReply settlement on all paths.

The batched apply engine's contract is that 'reply sent' means 'side
effect durable': a handler returns ``DeferredReply(future)`` and the
serving loop parks the reply until the apply thread resolves the future.
That contract has two static obligations this checker enforces:

1. **Creation**: a ``DeferredReply(...)`` must be RETURNED to the RPC
   layer (directly, or via a name that reaches a ``return``). A deferred
   reply constructed and dropped is a client parked forever — no one
   else holds the future's consumer side.

2. **Settlement**: a function that accumulates deferred replies (a local
   list whose name contains ``deferred``, paired with a local helper
   whose name contains ``settle``) must settle on EVERY exit path,
   exception edges included. Concretely: either the function drains the
   deferred list in a ``finally`` (covering every edge at once), or
   every ``return`` after the first accumulation is preceded, in its own
   block, by a call to the settle helper. A bare ``return`` inside an
   ``except`` handler is exactly the edge that silently strands a parked
   apply — the bug class this checker exists for.
"""

from __future__ import annotations

import ast

from parameter_server_tpu.analysis.core import (
    Finding,
    PackageIndex,
    iter_functions,
)


def _contains_call_to(node: ast.AST, names: set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = sub.func
            if isinstance(fn, ast.Name) and fn.id in names:
                return True
            if isinstance(fn, ast.Attribute) and fn.attr in names:
                return True
    return False


def _mentions_name(node: ast.AST, names: set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in names:
            return True
    return False


def _check_creation(
    relpath: str, fndef: ast.FunctionDef, out: list[Finding]
) -> None:
    returned_names: set[str] = set()
    returned_calls: set[int] = set()
    for sub in ast.walk(fndef):
        if isinstance(sub, ast.Return) and sub.value is not None:
            for x in ast.walk(sub.value):
                if isinstance(x, ast.Name):
                    returned_names.add(x.id)
                if isinstance(x, ast.Call):
                    returned_calls.add(id(x))
    for sub in ast.walk(fndef):
        if not (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Name)
            and sub.func.id == "DeferredReply"
        ):
            continue
        if id(sub) in returned_calls:
            continue
        # assigned to a name that some return mentions?
        assigned_ok = False
        for st in ast.walk(fndef):
            if isinstance(st, ast.Assign) and any(
                id(c) == id(sub) for c in ast.walk(st.value)
            ):
                for t in st.targets:
                    if isinstance(t, ast.Name) and t.id in returned_names:
                        assigned_ok = True
        if not assigned_ok:
            out.append(Finding(
                "settle-exactly-once", relpath, sub.lineno,
                "DeferredReply constructed but never returned to the RPC "
                "layer: its future has no consumer and the caller parks "
                "forever",
            ))


def _settle_returns(
    relpath: str, fndef: ast.FunctionDef, out: list[Finding]
) -> None:
    # local deferred-accumulator lists + local settle helpers
    deferred_names = set()
    for sub in ast.walk(fndef):
        targets: list[ast.expr] = []
        if isinstance(sub, ast.Assign) and isinstance(
            sub.value, (ast.List, ast.ListComp)
        ):
            targets = sub.targets
        elif isinstance(sub, ast.AnnAssign) and isinstance(
            sub.value, (ast.List, ast.ListComp)
        ):
            targets = [sub.target]
        for t in targets:
            if isinstance(t, ast.Name) and "deferred" in t.id:
                deferred_names.add(t.id)
    settle_names = {
        sub.name
        for sub in ast.walk(fndef)
        if isinstance(sub, ast.FunctionDef) and "settle" in sub.name
    }
    if not deferred_names or not settle_names:
        return
    first_append = min(
        (
            sub.lineno
            for sub in ast.walk(fndef)
            if isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "append"
            and isinstance(sub.func.value, ast.Name)
            and sub.func.value.id in deferred_names
        ),
        default=None,
    )
    if first_append is None:
        return
    # finally-coverage: a finally that settles (or drains the list)
    # covers every exit edge at once
    for sub in ast.walk(fndef):
        if isinstance(sub, ast.Try) and sub.finalbody:
            fin = ast.Module(body=sub.finalbody, type_ignores=[])
            if _contains_call_to(fin, settle_names) or _mentions_name(
                fin, deferred_names
            ):
                return
    # no blanket coverage: every return past the first accumulation must
    # be locally preceded by a settle call

    def scan_block(body: list[ast.stmt]) -> None:
        for i, stmt in enumerate(body):
            if isinstance(stmt, ast.FunctionDef):
                continue  # helpers check their own bodies
            if (
                isinstance(stmt, ast.Return)
                and stmt.lineno > first_append
            ):
                prefix = ast.Module(body=body[:i], type_ignores=[])
                if not _contains_call_to(prefix, settle_names):
                    out.append(Finding(
                        "settle-exactly-once", relpath, stmt.lineno,
                        "exit path returns without settling deferred "
                        "replies (no settle call on this edge and no "
                        "finally drains the list): a parked apply's "
                        "reply — or its error — is silently dropped",
                    ))
                continue
            # recurse into nested statement blocks
            for attr in ("body", "orelse", "finalbody"):
                sub_body = getattr(stmt, attr, None)
                if isinstance(sub_body, list) and sub_body and isinstance(
                    sub_body[0], ast.stmt
                ):
                    scan_block(sub_body)
            for h in getattr(stmt, "handlers", []):
                scan_block(h.body)

    scan_block(fndef.body)


def check_settle_exactly_once(index: PackageIndex) -> list[Finding]:
    out: list[Finding] = []
    for f in index.files:
        if f.relpath.startswith("analysis/"):
            continue
        for _cls, fndef in iter_functions(f.tree):
            _check_creation(f.relpath, fndef, out)
            _settle_returns(f.relpath, fndef, out)
    return out
