"""Checkers ``counter-contract`` and ``config-contract``: derived
inventories instead of hand-maintained lists.

PR 4's ``tests/test_contracts.py`` asserted "every counter is visible in
the dashboard" and "every config key read has a default" against
regex-scanned inventories maintained inside the test. These checkers
derive the same inventories from the AST — one source of truth the tests
now import (``counter_inventory`` / ``config_key_usage``), so the lists
can never drift from the code again:

- every literal counter name bumped through ``wire_counters.inc`` /
  ``observe_max`` / ``inc_many`` must appear in the rendered
  ``format_cluster_stats`` dashboard (a renamed or filtered counter
  fails the build, not the on-call engineer reading a blank column);
- every ``cfg.<section>.<key>`` attribute read (aliases like
  ``scfg = server_cfg or ServerConfig()`` included) must be a declared
  dataclass field WITH a default in ``utils/config.py`` — a knob read
  by code but absent from the config schema crashes only at runtime,
  on the one cluster that sets it.
"""

from __future__ import annotations

import ast
import dataclasses

from parameter_server_tpu.analysis.core import Finding, PackageIndex

Sites = list[tuple[str, int]]


def counter_inventory(index: PackageIndex) -> dict[str, Sites]:
    """Every literal counter name bumped via ``wire_counters`` and the
    sites bumping it (the dashboard-visibility contract's left side)."""
    out: dict[str, Sites] = {}

    def add(name: str, relpath: str, line: int) -> None:
        out.setdefault(name, []).append((relpath, line))

    for f in index.files:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "wire_counters"
            ):
                continue
            if fn.attr in ("inc", "observe_max") and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                    add(a0.value, f.relpath, node.lineno)
            elif fn.attr == "inc_many" and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Dict):
                    for k in a0.keys:
                        if isinstance(k, ast.Constant) and isinstance(
                            k.value, str
                        ):
                            add(k.value, f.relpath, node.lineno)
    return out


def check_counter_contract(index: PackageIndex) -> list[Finding]:
    from parameter_server_tpu.utils.metrics import format_cluster_stats

    inv = counter_inventory(index)
    if not inv:
        return []
    rendered = format_cluster_stats({
        "nodes": {},
        "merged": {
            "counters": {n: 1 for n in inv}, "hists": {}, "timers": {},
        },
    })
    out: list[Finding] = []
    for name in sorted(inv):
        if name not in rendered:
            rel, line = inv[name][0]
            out.append(Finding(
                "counter-contract", rel, line,
                f"counter {name!r} is bumped here but invisible to "
                "format_cluster_stats — the dashboard would silently "
                "hide it; render it (or drop the counter)",
            ))
    return out


def _config_sections() -> dict[str, type]:
    from parameter_server_tpu.utils import config as config_mod

    return dict(config_mod._NESTED)


def config_key_usage(index: PackageIndex) -> dict[str, dict[str, Sites]]:
    """Every ``cfg.<section>.<key>`` read in the package (plus aliased
    reads: ``x = cfg.<section>`` / ``x = <SectionCfg>()`` /
    ``x = param or <SectionCfg>()``), keyed section -> key -> sites."""
    sections = _config_sections()
    class_to_section = {cls.__name__: s for s, cls in sections.items()}
    out: dict[str, dict[str, Sites]] = {}

    def add(section: str, key: str, relpath: str, line: int) -> None:
        out.setdefault(section, {}).setdefault(key, []).append(
            (relpath, line)
        )

    def is_cfg_base(expr: ast.AST) -> bool:
        return (isinstance(expr, ast.Name) and expr.id in ("cfg", "config")) or (
            isinstance(expr, ast.Attribute) and expr.attr in ("cfg", "_cfg")
        )

    def collect_aliases(scope: ast.AST) -> dict[str, str]:
        aliases: dict[str, str] = {}
        for node in ast.walk(scope):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            value = node.value
            if value is None:
                continue
            section = None
            if isinstance(value, ast.Attribute) and is_cfg_base(value.value):
                if value.attr in sections:
                    section = value.attr
            else:
                for sub in ast.walk(value):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id in class_to_section
                    ):
                        section = class_to_section[sub.func.id]
            if section is not None:
                for t in targets:
                    if isinstance(t, ast.Name):
                        aliases[t.id] = section
        return aliases

    def scan(scope: ast.AST, relpath: str) -> None:
        # aliases stay scoped to the function that binds them (a module-
        # wide map would let one function's `m = cfg.mf` relabel another
        # function's unrelated `m.foo` as a config read)
        aliases = collect_aliases(scope)
        for node in ast.walk(scope):
            if not isinstance(node, ast.Attribute):
                continue
            v = node.value
            # cfg.<section>.<key>
            if (
                isinstance(v, ast.Attribute)
                and v.attr in sections
                and is_cfg_base(v.value)
            ):
                add(v.attr, node.attr, relpath, node.lineno)
            # <alias>.<key>
            elif isinstance(v, ast.Name) and v.id in aliases:
                add(aliases[v.id], node.attr, relpath, node.lineno)

    from parameter_server_tpu.analysis.core import iter_functions

    for f in index.files:
        for _cls, fndef in iter_functions(f.tree):
            scan(fndef, f.relpath)
        # module-level statements (outside any function)
        mod_only = ast.Module(
            body=[
                s
                for s in f.tree.body
                if not isinstance(
                    s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                )
            ],
            type_ignores=[],
        )
        scan(mod_only, f.relpath)
    return out


def check_config_contract(index: PackageIndex) -> list[Finding]:
    sections = _config_sections()
    usage = config_key_usage(index)
    out: list[Finding] = []
    for section, keys in sorted(usage.items()):
        cls = sections[section]
        fields = {fld.name: fld for fld in dataclasses.fields(cls)}
        for key, sites in sorted(keys.items()):
            rel, line = sites[0]
            fld = fields.get(key)
            if fld is None:
                out.append(Finding(
                    "config-contract", rel, line,
                    f"[{section}] key {key!r} is read here but "
                    f"{cls.__name__} declares no such field — this "
                    "crashes at runtime on any config that reaches it",
                ))
            elif (
                fld.default is dataclasses.MISSING
                and fld.default_factory is dataclasses.MISSING
            ):
                out.append(Finding(
                    "config-contract", rel, line,
                    f"[{section}] key {key!r} has no default in "
                    f"{cls.__name__}: every config file would be forced "
                    "to set it",
                ))
    return out
