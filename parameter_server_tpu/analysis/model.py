"""psmc — explicit-state model checker for the package's core protocols.

pslint (PR 5/8) proves properties of the CODE: lock orders, RCU alias
discipline, wire-table lockstep. What it cannot prove is that the
PROTOCOLS those mechanisms implement are correct — that the cid/seq
dedup + durable ledger + reply cache composition really yields
exactly-once pushes across crash/restart, that the RCU publish really
never shows a torn (state, version) pair, that the SSP clock's
retire/reassign really cannot wedge live workers, and that direction
#1's chain-replication failover really loses nothing mid-window. Those
are *state-space* properties: the bugs live in interleavings of sends,
drops, duplicates, crashes and promotions that no single test run
walks.

This module is the smallest checker that walks ALL of them, bounded:

- **Explicit-state BFS** with state hashing: every spec state is a
  hashable value (``freeze`` canonicalizes dicts/sets); the frontier
  expands breadth-first, so any counterexample found is a SHORTEST one.
- **Bounded**: specs bound their process/message/crash counts in a
  ``Bounds``-style dataclass; the engine additionally caps explored
  states (``max_states``) and reports whether exploration was
  exhaustive (``complete``) — "verified" claims are only made on
  complete runs.
- **Invariant checks** at every reached state; **fairness-bounded
  liveness** at every *quiescent* state (no enabled actions): under the
  fairness assumption that enabled actions eventually fire, a liveness
  property reduces to "every state where nothing is enabled satisfies
  the goal" — a deadlocked gate or a lost acked push shows up as a
  quiescent state that fails it.
- **Counterexample traces as replayable step lists**: the action labels
  from an initial state to the violating state, exactly the argument
  the next engineer needs to replay the failure by hand against the
  spec (and against the code it models).
- **Seeded deep probe**: when BFS hits the state cap, ``probe_seeds``
  seeded random walks continue past the frontier — not a proof, but a
  deterministic (same seed => same walks) bug-finder for bounds too big
  to exhaust.

Specs live in ``analysis/specs/`` (one module per protocol, registered
in ``specs.SPECS``); each declares the ASSUMPTIONS it makes about the
real code, which ``analysis/conformance.py`` diffs against tables
derived from the AST — the model and the code cannot drift apart
silently. ``cli check`` runs the whole suite at tier-1 bounds.
"""

from __future__ import annotations

import random
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Hashable


def freeze(v: Any) -> Hashable:
    """Canonical hashable form of a spec state fragment: dicts become
    sorted (key, value) tuples, sets become sorted tuples, lists become
    tuples — recursively, so specs can build states from plain Python
    and the engine can hash them."""
    if isinstance(v, dict):
        return tuple(sorted((k, freeze(x)) for k, x in v.items()))
    if isinstance(v, (set, frozenset)):
        return tuple(sorted(freeze(x) for x in v))
    if isinstance(v, (list, tuple)):
        return tuple(freeze(x) for x in v)
    return v


class Spec:
    """One protocol model. Subclasses implement the four hooks; states
    must be hashable (use :func:`freeze`) and actions must enumerate in
    a deterministic order — BFS determinism (same bounds => same state
    count, same counterexample) is the property the tests pin."""

    name: str = "spec"

    def init_states(self) -> list[Hashable]:
        raise NotImplementedError

    def actions(self, state: Hashable) -> list[tuple[str, Hashable]]:
        """Enabled transitions as (label, successor) pairs."""
        raise NotImplementedError

    def invariant(self, state: Hashable) -> str | None:
        """Violation message, or None. Checked at EVERY reached state."""
        return None

    def liveness(self, state: Hashable) -> str | None:
        """Violation message, or None. Checked at QUIESCENT states only
        (no enabled actions): under fairness, 'eventually P' reduces to
        'P holds wherever the system can no longer move' — a deadlock
        is a quiescent state that fails the goal."""
        return None


@dataclass
class Violation:
    kind: str  # invariant | liveness
    message: str
    trace: list[str]  # action labels, init -> violating state
    state: Hashable

    def render(self) -> str:
        steps = "\n".join(
            f"  {i + 1:>3}. {a}" for i, a in enumerate(self.trace)
        ) or "  (initial state)"
        return (
            f"{self.kind} violation: {self.message}\n"
            f"replayable steps ({len(self.trace)}):\n{steps}"
        )


@dataclass
class CheckResult:
    spec: str
    states: int = 0
    transitions: int = 0
    depth: int = 0
    complete: bool = True  # exhausted the bounded space (no cap hit)
    violation: Violation | None = None

    @property
    def ok(self) -> bool:
        return self.violation is None

    def summary(self) -> dict:
        return {
            "spec": self.spec,
            "states": self.states,
            "transitions": self.transitions,
            "depth": self.depth,
            "complete": self.complete,
            "ok": self.ok,
            "violation": (
                None
                if self.violation is None
                else {
                    "kind": self.violation.kind,
                    "message": self.violation.message,
                    "trace": list(self.violation.trace),
                }
            ),
        }


@dataclass
class _Node:
    parent: Hashable | None
    label: str | None
    depth: int = 0


def _trace(nodes: dict[Hashable, _Node], state: Hashable) -> list[str]:
    out: list[str] = []
    cur: Hashable | None = state
    while cur is not None:
        n = nodes[cur]
        if n.label is not None:
            out.append(n.label)
        cur = n.parent
    return out[::-1]


def check(
    spec: Spec,
    max_states: int = 200_000,
    max_depth: int = 0,
    probe_seeds: int = 0,
    probe_len: int = 256,
    seed: int = 0,
) -> CheckResult:
    """Exhaustive bounded BFS over ``spec``'s state space. Deterministic:
    same spec + bounds => same state count, same (shortest)
    counterexample. ``probe_seeds`` > 0 adds seeded random walks past
    the BFS cap when the cap was hit (bug probing, not verification —
    ``complete`` stays False)."""
    res = CheckResult(spec=spec.name)
    nodes: dict[Hashable, _Node] = {}
    q: deque[Hashable] = deque()
    for s in spec.init_states():
        if s in nodes:
            continue
        nodes[s] = _Node(None, None, 0)
        msg = spec.invariant(s)
        if msg is not None:
            res.states = len(nodes)
            res.violation = Violation("invariant", msg, [], s)
            return res
        q.append(s)
    while q:
        if len(nodes) >= max_states:
            res.complete = False
            break
        s = q.popleft()
        depth = nodes[s].depth
        res.depth = max(res.depth, depth)
        acts = spec.actions(s)
        if not acts:
            msg = spec.liveness(s)
            if msg is not None:
                res.states = len(nodes)
                res.violation = Violation(
                    "liveness", msg, _trace(nodes, s), s
                )
                return res
            continue
        if max_depth and depth >= max_depth:
            res.complete = False
            continue
        for label, nxt in acts:
            res.transitions += 1
            if nxt in nodes:
                continue
            nodes[nxt] = _Node(s, label, depth + 1)
            msg = spec.invariant(nxt)
            if msg is not None:
                res.states = len(nodes)
                res.violation = Violation(
                    "invariant", msg, _trace(nodes, nxt), nxt
                )
                return res
            q.append(nxt)
    res.states = len(nodes)
    if not res.complete and probe_seeds > 0 and res.violation is None:
        v = _probe(spec, probe_seeds, probe_len, seed)
        if v is not None:
            res.violation = v
    return res


def _probe(
    spec: Spec, walks: int, length: int, seed: int
) -> Violation | None:
    """Seeded random walks (deterministic per seed): a cheap deep probe
    for state spaces the BFS cap cut short. Invariants checked per step,
    liveness at any quiescent endpoint."""
    for w in range(walks):
        rng = random.Random(f"{seed}:{w}")
        inits = spec.init_states()
        s = inits[rng.randrange(len(inits))]
        trace: list[str] = []
        for _ in range(length):
            msg = spec.invariant(s)
            if msg is not None:
                return Violation("invariant", msg, trace, s)
            acts = spec.actions(s)
            if not acts:
                msg = spec.liveness(s)
                if msg is not None:
                    return Violation("liveness", msg, trace, s)
                break
            label, s2 = acts[rng.randrange(len(acts))]
            trace.append(label)
            s = s2
        # the loop checks invariants at the TOP of each iteration, so a
        # walk whose final transition lands on a violating state would
        # otherwise slip out unchecked
        msg = spec.invariant(s)
        if msg is not None:
            return Violation("invariant", msg, trace, s)
    return None
