"""One shared dataflow fixpoint per PackageIndex (ISSUE 20).

The package-wide summary fixpoint in :class:`DataflowAnalysis` is the
expensive half of every dataflow-backed checker (~2.5s over the real
package). pslint v2 had two such checkers, each running its own
fixpoint; v3 adds three more (units / clockdomain / idtype). Five
independent fixpoints would blow the "full lint must not regress >1.5x"
budget — so this module gives them ONE:

- checker modules call :func:`register_flow_policy` at import time with
  a factory ``(PackageIndex) -> FlowPolicy | None`` (None: the policy
  has nothing to look for in this index, e.g. no RCU publishers);
- the first checker to call :func:`flow_policy` triggers a single
  :class:`DataflowAnalysis` run over a :class:`CompositePolicy` of
  every registered policy (disjoint tag namespaces via
  ``FlowPolicy.owns``), cached per index in a WeakKeyDictionary —
  the same pattern ``callgraph.shared_callgraph`` uses;
- every later checker on the same index gets its (already-populated)
  policy back for free and just converts its findings.

Factories register at import time instead of being imported here so the
dependency arrow stays acyclic: flowrun knows no checker module, every
checker module knows flowrun.
"""

from __future__ import annotations

import weakref
from typing import Callable

from parameter_server_tpu.analysis.callgraph import shared_callgraph
from parameter_server_tpu.analysis.core import PackageIndex
from parameter_server_tpu.analysis.dataflow import (
    CompositePolicy,
    DataflowAnalysis,
    FlowPolicy,
)

PolicyFactory = Callable[[PackageIndex], "FlowPolicy | None"]

_FACTORIES: dict[str, PolicyFactory] = {}
_RUNS: "weakref.WeakKeyDictionary[PackageIndex, dict[str, FlowPolicy]]" = (
    weakref.WeakKeyDictionary()
)


def register_flow_policy(name: str, factory: PolicyFactory) -> None:
    """Idempotent (module re-imports just overwrite with the same fn)."""
    _FACTORIES[name] = factory


def flow_policy(index: PackageIndex, name: str) -> FlowPolicy | None:
    """The named policy, its findings already populated by the shared
    run over ``index`` (None if its factory declined this index)."""
    run = _RUNS.get(index)
    if run is None:
        run = _compute(index)
        _RUNS[index] = run
    return run.get(name)


def _compute(index: PackageIndex) -> dict[str, FlowPolicy]:
    graph = shared_callgraph(index)
    policies: dict[str, FlowPolicy] = {}
    # deterministic composition order (registration order is import
    # order, which varies with entry point)
    for name in sorted(_FACTORIES):
        p = _FACTORIES[name](index)
        if p is not None:
            policies[name] = p
    if policies:
        DataflowAnalysis(
            index, CompositePolicy(list(policies.values())), graph
        ).run()
    return policies
