"""Checker ``blocking-under-lock``: no blocking call while holding a lock.

A lock in this codebase protects nanosecond-scale state transitions
(counter bumps, dict swaps, window bookkeeping). Anything that can park
the holding thread — socket sends/receives, ``time.sleep``,
``Future.result``, an RPC ``call``/``call_async``, a jit dispatch or
device sync (``asarray``/``device_get``/``block_until_ready``), waiting
on a foreign Event/Condition — stalls every other thread contending for
that lock for the call's full duration, which is exactly how the reader
thread ends up unable to complete the reply the blocked send is waiting
for. The checker walks every function with the held-lock stack and flags
blocking primitives (directly, or through a call whose transitive
summary may block).

``cv.wait()`` / ``cv.wait_for()`` on the condition being held is NOT
flagged: a condition wait releases its lock — that's the one sanctioned
way to block "under" a lock.

Deliberate exceptions (a lock whose entire purpose is serializing a
blocking operation, like the client's socket-write lock) carry a
``# psl: ignore[blocking-under-lock]: <why>`` pragma at the call site.
"""

from __future__ import annotations

import ast

from parameter_server_tpu.analysis.callgraph import (
    CallGraph,
    OwnerKey,
    shared_callgraph,
)
from parameter_server_tpu.analysis.core import (
    Finding,
    HeldLockWalker,
    PackageIndex,
    iter_functions,
    unparse,
)

#: attribute / function names that park the calling thread
BLOCKING_ATTRS = frozenset({
    # sockets
    "sendall", "sendmsg", "send", "sendto",
    "recv", "recv_into", "recvfrom", "accept", "connect",
    # time
    "sleep",
    # futures / RPC round trips
    "result", "call", "call_async",
    # device sync / jit dispatch boundaries
    "asarray", "device_get", "block_until_ready",
})

#: blocking only when the receiver is NOT the lock being held (a
#: condition wait releases its own lock; an Event.wait under a DIFFERENT
#: lock holds that lock for the whole park)
WAIT_ATTRS = frozenset({"wait", "wait_for"})


def _blocks_directly(fndef: ast.AST) -> bool:
    for sub in ast.walk(fndef):
        if isinstance(sub, ast.Call) and _blocking_name(sub) is not None:
            return True
    return False


def _blocking_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in BLOCKING_ATTRS:
        return fn.attr
    return None


def may_block_summaries(graph: CallGraph) -> dict[OwnerKey, bool]:
    return graph.summarize(
        direct=lambda owner, rp, cn, fd: _blocks_directly(fd),
        merge=lambda a, b: a or b,
        bottom=lambda: False,
    )


class _BlockWalker(HeldLockWalker):
    def __init__(
        self,
        graph: CallGraph,
        relpath: str,
        cls_name: str | None,
        summaries: dict[OwnerKey, bool],
        out: list[Finding],
    ):
        super().__init__(self._lock_key)
        self._graph = graph
        self._relpath = relpath
        self._cls = cls_name
        self._summaries = summaries
        self._out = out
        self._seen: set[int] = set()  # one finding per line

    def _lock_key(self, expr: ast.AST) -> str | None:
        g = self._graph
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and self._cls is not None
        ):
            return g.lock_attr_key(self._cls, expr.attr)
        if isinstance(expr, ast.Name):
            return g.module_locks.get(expr.id)
        return None

    def on_call(self, node: ast.Call, held: list) -> None:
        if not held:
            return
        what: str | None = None
        name = _blocking_name(node)
        if name is not None:
            what = f"{unparse(node.func)}(...)"
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in WAIT_ATTRS
        ):
            recv = unparse(node.func.value)
            if all(recv != expr for _, expr, _ in held):
                what = (
                    f"{unparse(node.func)}(...) "
                    "(waits on an object that is not the held lock)"
                )
        else:
            for callee in self._graph.callees(self._relpath, self._cls, node):
                if self._summaries.get(callee):
                    what = (
                        f"{unparse(node.func)}(...) "
                        f"(transitively blocking via {callee[1]}.{callee[2]})"
                    )
                    break
        if what is None or node.lineno in self._seen:
            return
        self._seen.add(node.lineno)
        locks = ", ".join(sorted({k for k, _, _ in held}))
        self._out.append(Finding(
            "blocking-under-lock", self._relpath, node.lineno,
            f"{what} while holding {locks}: the holder parks every "
            "thread contending for the lock for the call's duration",
        ))


def check_blocking_under_lock(index: PackageIndex) -> list[Finding]:
    graph = shared_callgraph(index)
    summaries = may_block_summaries(graph)
    out: list[Finding] = []
    for f in index.files:
        for cls_name, fndef in iter_functions(f.tree):
            _BlockWalker(graph, f.relpath, cls_name, summaries, out).walk_function(
                fndef
            )
    return out
