"""Checker ``flightrec-contract``: the flight-recorder event inventory
and the postmortem plane's event tables stay in lockstep.

``flightrec.record("<etype>", ...)`` call sites are scattered across
the wire, apply, clock, serving and heartbeat layers, and
``utils/postmortem.py`` interprets the dump stream with LITERAL etype
tables — the (cid, seq) stitcher, the anomaly detectors
(``apply.commit``, ``rcu.publish``, ``rpc.heal.*``, ``serve.shed``)
and the declared pass-through inventory ``_CONTEXT_EVENTS``. Both
sides are string literals, so a renamed event silently becomes an
anomaly detector that never fires again (a version-regression stream
the postmortem no longer reads is the expensive failure: the tooling
looks armed and is blind), and a new ``record()`` call the postmortem
plane never heard of is wreckage nobody will interpret.

Derived inventories, diffed both ways:

- **emitted**: every string the first argument of a
  ``flightrec.record(...)`` call (module alias or ``from ... import
  record``) can evaluate to — IfExp/BoolOp branches included;
- **known**: every etype literal ``utils/postmortem.py`` compares or
  membership-tests against an ``[\"etype\"]`` subscript, plus the
  ``_CONTEXT_EVENTS`` pass-through inventory, plus (ISSUE 14) every
  ``EVENTS`` frozenset a streaming monitor declares in
  ``analysis/monitors.py`` — the postmortem's protocol detectors ARE
  those monitors now, so the registry's consumed-event sets are the
  detector tables.

An emitted event the postmortem doesn't know is a finding at the
``record`` call site (add it to a detector or to ``_CONTEXT_EVENTS`` —
deliberately, in review); a known/stitched name nobody emits is a
finding at the postmortem table (the rename drift). Skipped entirely
for trees without ``utils/postmortem.py`` (snippet indexes opt in by
providing one).
"""

from __future__ import annotations

import ast

from parameter_server_tpu.analysis.core import Finding, PackageIndex

_FLIGHTREC_MOD = "parameter_server_tpu.utils.flightrec"
_POSTMORTEM_REL = "utils/postmortem.py"


def _str_consts(expr: ast.AST) -> set[str]:
    return {
        n.value
        for n in ast.walk(expr)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    }


def _record_aliases(tree: ast.Module) -> tuple[set[str], set[str]]:
    """(module aliases of utils.flightrec, local names bound to its
    ``record``) for one file."""
    mods: set[str] = set()
    funcs: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                dotted = f"{node.module}.{a.name}"
                if dotted == _FLIGHTREC_MOD:
                    mods.add(a.asname or a.name)
                elif node.module == _FLIGHTREC_MOD and a.name == "record":
                    funcs.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == _FLIGHTREC_MOD:
                    # `import pkg.utils.flightrec as fr` binds fr;
                    # the PLAIN form binds only the top-level package,
                    # so calls arrive as the full dotted chain
                    mods.add(a.asname if a.asname else a.name)
    return mods, funcs


def _dotted_name(expr: ast.AST) -> str | None:
    """Flatten ``a.b.c`` attribute chains to ``"a.b.c"`` (None when the
    chain roots in anything but a bare name)."""
    parts: list[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def emitted_events(index: PackageIndex) -> dict[str, list[tuple[str, int]]]:
    """etype -> [(relpath, line)] over every ``record`` call site in
    the tree (inside flightrec.py itself, bare ``record(...)`` calls
    count — the module calls its own entry point from the crash
    hooks)."""
    out: dict[str, list[tuple[str, int]]] = {}
    for f in index.files:
        mods, funcs = _record_aliases(f.tree)
        if f.relpath == _POSTMORTEM_REL:
            continue  # the consumer: reads events, never emits
        if f.relpath == _FLIGHTREC_REL:
            funcs = funcs | {"record"}
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            hit = (
                isinstance(fn, ast.Attribute)
                and fn.attr == "record"
                and _dotted_name(fn.value) in mods
            ) or (isinstance(fn, ast.Name) and fn.id in funcs)
            if not hit:
                continue
            for name in _str_consts(node.args[0]):
                out.setdefault(name, []).append((f.relpath, node.lineno))
    return out


_FLIGHTREC_REL = "utils/flightrec.py"


def _is_etype_expr(expr: ast.AST) -> bool:
    """``ev["etype"]`` / ``e["etype"]``-shaped subscripts (the
    postmortem's normalized event dicts)."""
    return (
        isinstance(expr, ast.Subscript)
        and isinstance(expr.slice, ast.Constant)
        and expr.slice.value == "etype"
    )


_MONITORS_REL = "analysis/monitors.py"


def _monitor_declared_events(
    index: PackageIndex,
) -> dict[str, list[tuple[str, int]]]:
    """etype -> sites for every ``EVENTS = frozenset({...})`` a
    streaming monitor declares (ISSUE 14): the monitors are the
    postmortem's detectors, so their consumed sets count as known."""
    mf = index.get(_MONITORS_REL)
    out: dict[str, list[tuple[str, int]]] = {}
    if mf is None:
        return out
    for node in ast.walk(mf.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        if node.value is None:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "EVENTS":
                for name in _str_consts(node.value):
                    out.setdefault(name, []).append(
                        (_MONITORS_REL, node.lineno)
                    )
    return out


def known_events(index: PackageIndex) -> dict[str, list[tuple[str, int]]]:
    """etype -> [(relpath, line)] the diagnostic plane handles: every
    literal compared/membership-tested against an etype subscript in
    the postmortem, the ``_CONTEXT_EVENTS`` inventory, and the
    streaming monitors' declared ``EVENTS`` sets."""
    pm = index.get(_POSTMORTEM_REL)
    out: dict[str, list[tuple[str, int]]] = _monitor_declared_events(index)
    if pm is None:
        return out
    for node in ast.walk(pm.tree):
        if isinstance(node, ast.Compare) and len(node.comparators) == 1:
            left, right = node.left, node.comparators[0]
            if isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
                pair = (
                    (left, right) if _is_etype_expr(left)
                    else (right, left) if _is_etype_expr(right)
                    else None
                )
                if pair is not None:
                    for name in _str_consts(pair[1]):
                        out.setdefault(name, []).append(
                            (_POSTMORTEM_REL, node.lineno)
                        )
            elif isinstance(node.ops[0], (ast.In, ast.NotIn)):
                if _is_etype_expr(left):
                    for name in _str_consts(right):
                        out.setdefault(name, []).append(
                            (_POSTMORTEM_REL, node.lineno)
                        )
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            if node.value is None:
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "_CONTEXT_EVENTS":
                    for name in _str_consts(node.value):
                        out.setdefault(name, []).append(
                            (_POSTMORTEM_REL, node.lineno)
                        )
    return out


def check_flightrec_contract(index: PackageIndex) -> list[Finding]:
    if index.get(_POSTMORTEM_REL) is None:
        return []  # no postmortem plane in this tree (snippet index)
    emitted = emitted_events(index)
    known = known_events(index)
    out: list[Finding] = []
    for name in sorted(set(emitted) - set(known)):
        relpath, line = emitted[name][0]
        out.append(Finding(
            "flightrec-contract", relpath, line,
            f"flight-recorder event {name!r} is emitted but the "
            "postmortem plane has never heard of it — wire it into an "
            "anomaly detector/stitch table or declare it in "
            "utils/postmortem.py _CONTEXT_EVENTS (deliberately, in "
            "review), or the wreckage it records will never be "
            "interpreted",
        ))
    for name in sorted(set(known) - set(emitted)):
        relpath, line = known[name][0]
        out.append(Finding(
            "flightrec-contract", relpath, line,
            f"the postmortem plane stitches/flags event {name!r} but "
            "no record() call emits it — the detector can never fire "
            "again (renamed or deleted event?); this is the silent "
            "failure mode of the whole diagnostic plane",
        ))
    return out
