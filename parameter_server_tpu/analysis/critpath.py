"""Cross-process critical-path engine (ISSUE 15): attribute one logical
push/pull's wall time to named pipeline segments.

The observability planes can say *that* p99 blew up; this module says
*why*: it stitches one logical operation across processes and splits its
client-observed wall time into the named phases of the pipeline —

- ``encode``       client-side payload encode before the RPC is issued
- ``client_queue`` window admission + frame build/queue (the
  ``rpc.<cmd>`` issue span)
- ``wire``         issue -> server dispatch: socket send, the network,
  server recv buffering (and any injected delay fault — this is where
  a straggling link shows up)
- ``server``       the server's dispatch span (decode, dedup, enqueue;
  the whole handler on the inline path)
- ``apply_wait``   batched-apply queue wait (dispatch end -> the apply
  thread picked the push up)
- ``apply``        the jitted coalesced apply itself
- ``reply_lane``   reply queued/withheld + the return wire
- ``ssp_wait``     the SSP gate (step-level ops)
- ``other``        whatever the instrumentation didn't cover (honesty
  column: attribution percentages must sum to ~100, not pretend to)

Two offline feeds, one stitch discipline:

- **trace mode** — a ``PS_TRACE_DIR`` capture: spans share a trace id
  across processes (the PR-2 propagation), flow events
  (``ps.<cmd>.inflight``) mark completion, and tail-capture sidecars
  (``tracetail-*.json``) are rescued for any trace id a main file
  retained, so the slow half of a cross-process op is present even
  when only one side promoted it;
- **blackbox mode** — a ``PS_BLACKBOX_DIR`` postmortem: flight-recorder
  events stitch by (cid, seq) (``rpc.issue`` -> ``rpc.in`` ->
  ``apply.commit`` -> ``rpc.reply``), the wreckage-grade segmentation
  when no trace was armed.

**Clock-skew hardening**: the stitch crosses wall clocks, and skewed
nodes can reorder a chain into negative segment durations. Negative
raw segments CLAMP to zero and flag the op ``skewed`` (surfaced in the
report and the aggregate) — attribution never reports negative time,
and a skew-heavy capture says so instead of bluffing.

``cli whylate`` is the surface: top-K slowest ops with per-segment
breakdowns over a trace/blackbox dir or a live cluster, plus
``--baseline`` per-segment latency budgets with tiered exit codes (the
pslint ``--baseline`` pattern) so CI fails on *which segment*
regressed.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any

#: canonical segment order (reports render in pipeline order)
SEGMENTS = (
    "encode", "client_queue", "wire", "server", "apply_wait", "apply",
    "reply_lane", "ssp_wait", "other",
)

#: negative-duration tolerance before an op is flagged skewed (us):
#: sub-millisecond inversions are clock granularity, not skew
_SKEW_EPS_US = 1000.0


# -- loading ----------------------------------------------------------------


def load_trace_dir(trace_dir: str) -> list[dict[str, Any]]:
    """Every span/flow/instant event of a trace-dir capture: the shared
    reader + sidecar-rescue rule from utils/trace.py (ONE definition of
    which limbo'd events join the capture), minus ``M`` metadata."""
    from parameter_server_tpu.utils import trace as trace_mod

    main, side = trace_mod.read_trace_dir(trace_dir)
    main.extend(trace_mod.rescue_sidecar_events(main, side))
    return [e for e in main if e.get("ph") != "M"]


def _percentile(vals: list[float], p: float) -> float:
    if not vals:
        return 0.0
    vs = sorted(vals)
    return vs[min(len(vs) - 1, max(0, math.ceil(p * len(vs)) - 1))]


def _clamp(raw_us: float, op: dict[str, Any]) -> float:
    """Negative raw segment -> 0 + the op's skew flag (satellite:
    cross-node wall-clock skew must clamp and flag, never report
    negative attribution)."""
    if raw_us < -_SKEW_EPS_US:
        op["skewed"] = True
    return max(raw_us, 0.0)


def _cap_to_total(
    seg_us: dict[str, float], total_us: float, op: dict[str, Any]
) -> None:
    """Skew's other face: a clock offset that deflates one segment
    inflates its complement past the op's wall time. Cap cumulative
    coverage at the total (pipeline order — seg_us insertion order) and
    flag the op, so attribution can never sum past 100%."""
    alloc = 0.0
    for k in list(seg_us):
        v = seg_us[k]
        if alloc + v > total_us + _SKEW_EPS_US:
            seg_us[k] = max(total_us - alloc, 0.0)
            op["skewed"] = True
        alloc += seg_us[k]


# -- trace mode -------------------------------------------------------------


def _span_end(e: dict[str, Any]) -> float:
    return e.get("ts", 0.0) + e.get("dur", 0.0)


def ops_from_trace(events: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """One op per stitched trace: client root span (``ps.<cmd>`` — or a
    parentless ``rpc.<cmd>`` for raw clients), the issue-side rpc span,
    the server dispatch span, the per-push updater marker and the
    completion flow event. Fan-out ops (one push over many shards) use
    the critical chain: the shard whose spans end LAST is the one the
    op actually waited for."""
    by_tid: dict[str, list[dict[str, Any]]] = {}
    for e in events:
        tid = (e.get("args") or {}).get("trace_id")
        if tid is not None:
            by_tid.setdefault(tid, []).append(e)
    ops: list[dict[str, Any]] = []
    for tid, evs in by_tid.items():
        spans = [e for e in evs if e.get("ph") == "X"]
        named: dict[str, list[dict[str, Any]]] = {}
        for s in spans:
            named.setdefault(s["name"], []).append(s)
        root = None
        for name in named:
            if name.startswith("ps.") and "." not in name[3:]:
                root = max(named[name], key=lambda s: s.get("dur", 0.0))
                break
        if root is None and named.get("step"):
            root = max(named["step"], key=lambda s: s.get("dur", 0.0))
        if root is None:
            # raw client: a parentless rpc.<cmd> span is the op
            cands = [
                s for s in spans
                if s["name"].startswith("rpc.")
                and not s["name"].startswith("rpc.serve.")
                and "parent_id" not in (s.get("args") or {})
            ]
            if cands:
                root = max(cands, key=lambda s: s.get("dur", 0.0))
        if root is not None and root["name"] == "step":
            ops.append(_step_op(tid, root, named))
            continue
        if root is None:
            continue
        cmd = root["name"].rsplit(".", 1)[-1]
        op: dict[str, Any] = {
            "cmd": cmd, "tid": tid, "skewed": False,
            "ts": root.get("ts", 0.0) / 1e6,
            "procs": len({e.get("pid") for e in evs}),
        }
        rpc = (
            max(named.get(f"rpc.{cmd}", []), key=_span_end)
            if named.get(f"rpc.{cmd}") and root["name"] != f"rpc.{cmd}"
            else root if root["name"] == f"rpc.{cmd}" else None
        )
        serve = (
            max(named.get(f"rpc.serve.{cmd}", []), key=_span_end)
            if named.get(f"rpc.serve.{cmd}") else None
        )
        upd = (
            max(named.get("server.updater", []), key=_span_end)
            if named.get("server.updater") else None
        )
        flows = [
            e for e in evs
            if e.get("ph") == "f" and e["name"] == f"ps.{cmd}.inflight"
        ]
        t0_us = root["ts"]
        done_us = max(
            [f["ts"] for f in flows] + [_span_end(s) for s in spans]
        )
        total_us = max(done_us - t0_us, 0.0)
        seg_us: dict[str, float] = {}
        if rpc is not None:
            seg_us["encode"] = _clamp(rpc["ts"] - t0_us, op)
            seg_us["client_queue"] = rpc.get("dur", 0.0)
            issue_end_us = _span_end(rpc)
        else:
            issue_end_us = t0_us
        if serve is not None:
            seg_us["wire"] = _clamp(serve["ts"] - issue_end_us, op)
            seg_us["server"] = serve.get("dur", 0.0)
            tail_start_us = _span_end(serve)
            # the apply segments exist only on the BATCHED path, where
            # the updater span runs on the apply thread after dispatch
            # returned; an updater span nested inside the serve span is
            # the inline path — its time is already in "server"
            if upd is not None and upd["ts"] >= tail_start_us:
                gap_us = _clamp(_span_end(upd) - tail_start_us, op)
                # the marker fires AFTER the apply with the MEASURED
                # jitted-apply time in its args (multislice stamps
                # apl_us) — a first-batch jit compile lands in "apply",
                # not in the queue-wait column; the gap's remainder is
                # the real apply_wait
                apl_us = min(
                    float((upd.get("args") or {}).get(
                        "apl_us", upd.get("dur", 0.0)
                    )),
                    gap_us,
                )
                seg_us["apply_wait"] = gap_us - apl_us
                seg_us["apply"] = apl_us
                tail_start_us = max(tail_start_us, _span_end(upd))
            seg_us["reply_lane"] = _clamp(done_us - tail_start_us, op)
        else:
            # server segment missing (not captured/rescued): everything
            # past the issue span is wire-or-beyond — an honest catch-all
            seg_us["wire"] = _clamp(done_us - issue_end_us, op)
        _cap_to_total(seg_us, total_us, op)
        covered = sum(seg_us.values())
        seg_us["other"] = max(total_us - covered, 0.0)
        op["dur_ms"] = round(total_us / 1e3, 3)
        op["segments"] = {
            k: round(v / 1e3, 3) for k, v in seg_us.items() if v > 0.0
        }
        op["pct"] = _pct(seg_us, total_us)
        ops.append(op)
    return ops


def _step_op(
    tid: str, root: dict[str, Any], named: dict[str, list[dict[str, Any]]]
) -> dict[str, Any]:
    """Worker step anatomy: the ``step`` span's children are already the
    segmentation (ssp_wait / pull / compute); pushes stay in flight past
    the span, so the step op covers the synchronous part only."""
    op: dict[str, Any] = {
        "cmd": "step", "tid": tid, "skewed": False,
        "ts": root.get("ts", 0.0) / 1e6, "procs": 1,
    }
    total_us = root.get("dur", 0.0)
    seg_us: dict[str, float] = {}
    for child, seg in (
        ("step.ssp_wait", "ssp_wait"),
        ("step.pull", "wire"),
        ("step.compute", "other"),
    ):
        if named.get(child):
            seg_us[seg] = sum(s.get("dur", 0.0) for s in named[child])
    covered = sum(seg_us.values())
    seg_us["other"] = seg_us.get("other", 0.0) + max(total_us - covered, 0.0)
    op["dur_ms"] = round(total_us / 1e3, 3)
    op["segments"] = {
        k: round(v / 1e3, 3) for k, v in seg_us.items() if v > 0.0
    }
    op["pct"] = _pct(seg_us, total_us)
    return op


def _pct(seg_us: dict[str, float], total_us: float) -> dict[str, float]:
    if total_us <= 0:
        return {}
    return {
        k: round(100.0 * v / total_us, 1)
        for k, v in seg_us.items() if v > 0.0
    }


# -- blackbox mode ----------------------------------------------------------


def ops_from_blackbox(
    timeline: list[dict[str, Any]],
) -> list[dict[str, Any]]:
    """(cid, seq)-stitched chains from flight-recorder wreckage:
    ``rpc.issue`` (client) -> ``rpc.in`` (server) -> ``apply.commit``
    (server, via its pairs) -> ``rpc.reply`` (client). Coarser than
    trace mode (three segments) but needs nothing armed beyond the
    always-on black box."""
    from parameter_server_tpu.utils.postmortem import stitch_calls

    ops: list[dict[str, Any]] = []
    for (cid, seq), evs in stitch_calls(timeline).items():
        issue = reply = first_in = commit = None
        cmd = None
        for e in evs:
            et = e["etype"]
            if et == "rpc.issue" and issue is None:
                issue, cmd = e, e["args"].get("cmd")
            elif et == "rpc.in" and first_in is None:
                first_in = e
            elif et in ("apply.commit", "apply.replay"):
                commit = e if commit is None else commit
            elif et == "rpc.reply":
                reply = e  # last reply wins: retries re-deliver
        if issue is None or reply is None:
            continue  # a half chain can't be segmented honestly
        op: dict[str, Any] = {
            "cmd": cmd or "?", "tid": f"{cid}/{seq}", "skewed": False,
            "ts": issue["ts"],
            "procs": len({(e["proc"], e["pid"]) for e in evs}),
        }
        t0_us = issue["ts"] * 1e6
        done_us = reply["ts"] * 1e6
        total_us = max(done_us - t0_us, 0.0)
        seg_us: dict[str, float] = {}
        if first_in is not None:
            in_ts_us = first_in["ts"] * 1e6
            seg_us["wire"] = _clamp(in_ts_us - t0_us, op)
            srv_end_us = in_ts_us
            if commit is not None:
                seg_us["server"] = _clamp(commit["ts"] * 1e6 - in_ts_us, op)
                srv_end_us = commit["ts"] * 1e6
            seg_us["reply_lane"] = _clamp(done_us - srv_end_us, op)
        _cap_to_total(seg_us, total_us, op)
        covered = sum(seg_us.values())
        seg_us["other"] = max(total_us - covered, 0.0)
        op["dur_ms"] = round(total_us / 1e3, 3)
        op["segments"] = {
            k: round(v / 1e3, 3) for k, v in seg_us.items() if v > 0.0
        }
        op["pct"] = _pct(seg_us, total_us)
        ops.append(op)
    return ops


# -- aggregation ------------------------------------------------------------


def aggregate(
    ops: list[dict[str, Any]], top: int = 5
) -> dict[str, dict[str, Any]]:
    """Per-cmd window view: op-latency p50/p99, per-segment p99s,
    duration-weighted attribution percentages, the top-K slowest ops
    (duration-descending, full breakdowns attached) and the skew
    count."""
    by_cmd: dict[str, list[dict[str, Any]]] = {}
    for op in ops:
        by_cmd.setdefault(op["cmd"], []).append(op)
    out: dict[str, dict[str, Any]] = {}
    for cmd, group in sorted(by_cmd.items()):
        durs = [op["dur_ms"] for op in group]
        seg_tot: dict[str, float] = {}
        seg_vals: dict[str, list[float]] = {}
        for op in group:
            for k, v in op.get("segments", {}).items():
                seg_tot[k] = seg_tot.get(k, 0.0) + v
                seg_vals.setdefault(k, []).append(v)
        total = sum(durs) or 1.0
        slowest = sorted(group, key=lambda o: -o["dur_ms"])[:top]
        out[cmd] = {
            "n": len(group),
            "p50_ms": round(_percentile(durs, 0.5), 3),
            "p99_ms": round(_percentile(durs, 0.99), 3),
            "attribution_pct": {
                k: round(100.0 * v / total, 1)
                for k, v in sorted(seg_tot.items(), key=lambda kv: -kv[1])
            },
            "segments_p99_ms": {
                k: round(_percentile(v, 0.99), 3)
                for k, v in sorted(seg_vals.items())
            },
            "slowest": slowest,
            "skewed": sum(1 for op in group if op.get("skewed")),
        }
    return out


def analyze_dir(path: str, top: int = 5) -> dict[str, Any]:
    """End-to-end over a capture dir, auto-detected: ``blackbox-*.json``
    dumps -> blackbox mode, else trace mode."""
    names = os.listdir(path)
    if any(
        fn.startswith("blackbox-") and fn.endswith(".json") for fn in names
    ):
        from parameter_server_tpu.utils.postmortem import (
            load_dumps,
            merge_timeline,
        )

        ops = ops_from_blackbox(merge_timeline(load_dumps(path)))
        mode = "blackbox"
    else:
        ops = ops_from_trace(load_trace_dir(path))
        mode = "trace"
    return {
        "mode": mode,
        "ops": len(ops),
        "skewed_ops": sum(1 for op in ops if op.get("skewed")),
        "cmds": aggregate(ops, top=top),
    }


def analyze_live(rep: dict[str, Any], top: int = 5) -> dict[str, Any]:
    """The live-cluster view from one coordinator ``telemetry`` reply:
    the heartbeat-piggybacked slowest-K records (utils/metrics.py
    SlowOps — client wall time split by the reply's server-timing echo)
    shaped like the offline aggregate so one renderer serves both."""
    merged = rep.get("merged") or {}
    cmds: dict[str, dict[str, Any]] = {}
    for cmd, recs in sorted((merged.get("slow") or {}).items()):
        ops = []
        for r in recs[:top]:
            seg = dict(r.get("seg") or {})
            dur = float(r.get("dur_ms", 0.0))
            covered = sum(seg.values())
            if seg and dur > covered:
                seg["other"] = round(dur - covered, 3)
            op = {
                "cmd": cmd, "dur_ms": dur, "segments": seg,
                "pct": {
                    k: round(100.0 * v / dur, 1)
                    for k, v in seg.items() if dur > 0
                },
                "ts": r.get("ts"), "skewed": False,
            }
            if r.get("tid"):
                op["tid"] = r["tid"]
            ops.append(op)
        seg_tot: dict[str, float] = {}
        for op in ops:
            for k, v in op["segments"].items():
                seg_tot[k] = seg_tot.get(k, 0.0) + v
        total = sum(op["dur_ms"] for op in ops) or 1.0
        cmds[cmd] = {
            "n": len(recs),
            "p50_ms": round(_percentile(
                [float(r.get("dur_ms", 0.0)) for r in recs], 0.5
            ), 3),
            "p99_ms": round(_percentile(
                [float(r.get("dur_ms", 0.0)) for r in recs], 0.99
            ), 3),
            "attribution_pct": {
                k: round(100.0 * v / total, 1)
                for k, v in sorted(seg_tot.items(), key=lambda kv: -kv[1])
            },
            "segments_p99_ms": {},
            "slowest": ops,
            "skewed": 0,
        }
    return {
        "mode": "live",
        "ops": sum(c["n"] for c in cmds.values()),
        "skewed_ops": 0,
        "cmds": cmds,
    }


# -- report -----------------------------------------------------------------


def render_report(summary: dict[str, Any], top: int = 5) -> str:
    """The human ``cli whylate`` output: per cmd, the window's latency
    and the slowest ops with their segment breakdowns."""
    lines = [
        f"whylate — {summary['ops']} op(s) stitched "
        f"({summary['mode']} mode)"
        + (
            f", {summary['skewed_ops']} clock-skew-clamped"
            if summary.get("skewed_ops") else ""
        )
    ]
    for cmd, agg in summary.get("cmds", {}).items():
        lines.append("")
        lines.append(
            f"{cmd}: n={agg['n']} p50={agg['p50_ms']}ms "
            f"p99={agg['p99_ms']}ms"
            + (f"  [{agg['skewed']} skewed]" if agg.get("skewed") else "")
        )
        att = agg.get("attribution_pct") or {}
        if att:
            lines.append(
                "  attribution: "
                + "  ".join(f"{k} {v}%" for k, v in att.items())
            )
        for op in (agg.get("slowest") or [])[:top]:
            segs = op.get("segments") or {}
            ordered = sorted(segs.items(), key=lambda kv: -kv[1])
            lines.append(
                f"  slow {op['dur_ms']:>9.3f}ms"
                + (f" tid={op['tid']}" if op.get("tid") else "")
                + (" SKEWED" if op.get("skewed") else "")
                + "  "
                + "  ".join(f"{k}={v}ms" for k, v in ordered)
            )
    if not summary.get("cmds"):
        lines.append("no stitchable ops found")
    return "\n".join(lines)


# -- baseline gate (the pslint --baseline pattern) --------------------------


def load_baseline(path: str) -> dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if "budgets_ms" not in doc:
        raise ValueError(
            f"{path}: not a whylate baseline (missing budgets_ms)"
        )
    return doc


def check_baseline(
    summary: dict[str, Any], baseline: dict[str, Any]
) -> list[dict[str, Any]]:
    """Per-segment budget findings: a (cmd, segment) whose measured p99
    exceeds its budget is a WARN; past ``hard_factor`` x budget it is an
    ERROR. Segments without budgets are ungated (new instrumentation
    never fails CI until someone budgets it)."""
    hard = float(baseline.get("hard_factor", 2.0))
    out: list[dict[str, Any]] = []
    for cmd, budgets in sorted((baseline.get("budgets_ms") or {}).items()):
        agg = (summary.get("cmds") or {}).get(cmd)
        if agg is None:
            continue  # nothing measured for this cmd: nothing regressed
        measured = agg.get("segments_p99_ms") or {}
        for seg, budget in sorted(budgets.items()):
            got = measured.get(seg)
            if got is None or got <= float(budget):
                continue
            out.append({
                "cmd": cmd,
                "segment": seg,
                "p99_ms": got,
                "budget_ms": float(budget),
                "tier": "error" if got > hard * float(budget) else "warn",
            })
    return out


def baseline_exit_code(findings: list[dict[str, Any]]) -> int:
    """pslint's tiered convention: 1 = hard (error-tier) regressions,
    2 = soft (warn-tier only), 0 = within budget."""
    if any(f["tier"] == "error" for f in findings):
        return 1
    return 2 if findings else 0


def update_baseline(
    summary: dict[str, Any], path: str, slack: float = 2.0
) -> dict[str, Any]:
    """Rewrite the baseline from the current capture: each measured
    per-segment p99 x ``slack`` becomes the budget (floored at 1 ms so
    scheduler jitter can't institutionalize a microsecond budget)."""
    budgets: dict[str, dict[str, float]] = {}
    for cmd, agg in (summary.get("cmds") or {}).items():
        segs = {
            seg: round(max(v * slack, 1.0), 3)
            for seg, v in (agg.get("segments_p99_ms") or {}).items()
        }
        if segs:
            budgets[cmd] = segs
    doc = {"version": 1, "hard_factor": 2.0, "budgets_ms": budgets}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return doc
