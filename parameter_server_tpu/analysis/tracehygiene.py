"""Checker ``trace-hygiene``: spans only via context manager/decorator.

The tracer's invariant is that every span that begins also ends — the
ring buffer and the Perfetto export assume balanced B/E events, and an
unclosed span corrupts every enclosing span's nesting for its thread. In
this codebase that invariant is carried entirely by ``with
trace.span(...)`` and ``@trace.traced(...)``: there is deliberately NO
public begin/end API. The checker enforces the idiom: any ``*.span(...)``
call that is not a ``with`` context item (and any direct ``Span(...)``
construction outside utils/trace.py itself) is a bare begin whose end
depends on control flow the tracer can't see.
"""

from __future__ import annotations

import ast

from parameter_server_tpu.analysis.core import Finding, PackageIndex

#: the implementation itself builds spans by hand
_IMPL = "utils/trace.py"


def check_trace_hygiene(index: PackageIndex) -> list[Finding]:
    out: list[Finding] = []
    for f in index.files:
        if f.relpath == _IMPL or f.relpath.startswith("analysis/"):
            continue
        with_items: set[int] = set()
        decorated: set[int] = set()
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Call):
                            with_items.add(id(sub))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    for sub in ast.walk(dec):
                        if isinstance(sub, ast.Call):
                            decorated.add(id(sub))
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "span"
                and id(node) not in with_items
                and id(node) not in decorated
            ):
                out.append(Finding(
                    "trace-hygiene", f.relpath, node.lineno,
                    "bare span(...) call outside a with statement: a span "
                    "opened without its context manager has no guaranteed "
                    "end event (use `with trace.span(...)` or "
                    "`@trace.traced`)",
                ))
            elif (
                isinstance(fn, ast.Name)
                and fn.id == "Span"
            ):
                out.append(Finding(
                    "trace-hygiene", f.relpath, node.lineno,
                    "direct Span construction outside utils/trace.py: "
                    "spans must come from trace.span()/traced() so "
                    "begin/end stay paired",
                ))
    return out
