"""Spec <-> code conformance: the psmc models (analysis/specs/) declare
the ASSUMPTIONS they make about the real package; this module DERIVES
the matching facts from the AST — through the same call-graph/held-lock
machinery the PR-5/PR-8 checkers use — and diffs the two, so the model
and ``parallel/multislice.py``/``control.py``/``ssp.py`` cannot drift
apart silently. A protocol refactor that invalidates a model assumption
fails ``cli lint`` (and ``cli check``) at the drifted site, with the
spec named; the fix is to change the model WITH the code, reviewed
together.

Derived tables (``derive_code_tables``):

- ``idempotent_cmds``: the reply-cache exemption set at the
  ledger-owning server's ``RpcServer(...)`` construction (reuses the
  replycache checker's extraction);
- ``push_rides_reply_cache``: "push" is served and NOT exempt — its
  replies must ride the exactly-once reply cache;
- ``ledger_record_under_apply_lock``: every ``self._record_push(...)``
  call site runs while holding a lock attribute of the owning class
  (the ledger record and the state mutation it witnesses are one
  atomic unit);
- ``ledger_checked_before_apply``: every method that both records
  pushes and publishes state reads ``self._applied_push`` (the dedup
  check) before the publish store;
- ``publish_sites``: the methods that store ``self._pub`` outside
  ``__init__`` (the RCU model assumes exactly the ``state`` setter);
- ``publish_bumps_version``: that setter derives the new version from
  ``_pub[1] + 1``;
- ``retire_delegates_to_finish``: ``SSPClock.retire`` rides
  ``finish(worker, RETIRED)`` — retirement takes the same notify path
  as progress.

Each table is derived only when its subsystem exists in the analyzed
tree (snippet indexes exercise single tables), and the checker
``spec-conformance`` emits one finding per drifted assumption. The
sibling checker ``model-invariants`` runs the tier-1-bounded model
suite itself inside lint, so a spec edit that breaks a protocol model
fails the same gate.
"""

from __future__ import annotations

import ast
from typing import Any

from parameter_server_tpu.analysis.callgraph import shared_callgraph
from parameter_server_tpu.analysis.core import (
    Finding,
    HeldLockWalker,
    PackageIndex,
)
from parameter_server_tpu.analysis.replycache import (
    declared_sets,
    served_cmds,
)

#: assumption key -> the spec facts are derived FOR (reported on drift)
_LEDGER_KEYS = (
    "idempotent_cmds",
    "push_rides_reply_cache",
    "ledger_record_under_apply_lock",
    "ledger_checked_before_apply",
)
_RCU_KEYS = ("publish_sites", "publish_bumps_version")
_SSP_KEYS = ("retire_delegates_to_finish",)


def _is_self_attr(node: ast.AST, attr: str | None = None) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and (attr is None or node.attr == attr)
    )


def _find_class(
    index: PackageIndex, predicate
) -> tuple[str, ast.ClassDef] | None:
    for f in index.files:
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ClassDef) and predicate(node):
                return f.relpath, node
    return None


def _defines_method(cls: ast.ClassDef, name: str) -> bool:
    return any(
        isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n.name == name
        for n in cls.body
    )


class _LockedCallScan(HeldLockWalker):
    """Collects whether every ``self.<method>(...)`` call of interest
    inside one function runs with at least one held lock."""

    def __init__(self, is_lock_expr, method: str):
        super().__init__(is_lock_expr)
        self._method = method
        self.calls: list[bool] = []  # held? per call site

    def on_call(self, node: ast.Call, held) -> None:
        if _is_self_attr(node.func, self._method):
            self.calls.append(bool(held))


def _ledger_tables(
    index: PackageIndex, relpath: str, cls: ast.ClassDef
) -> dict[str, Any]:
    graph = shared_callgraph(index)
    out: dict[str, Any] = {}
    # reply-cache exemptions at this class's RpcServer(...) site
    idem: set[str] = set()
    for kw, names, _line in declared_sets(cls):
        if kw == "idempotent_cmds":
            idem |= names
    served = served_cmds(cls)
    out["idempotent_cmds"] = frozenset(idem)
    out["push_rides_reply_cache"] = (
        "push" in served and "push" not in idem
    )

    def is_lock(expr: ast.AST) -> str | None:
        if _is_self_attr(expr):
            return graph.lock_attr_key(cls.name, expr.attr)
        return None

    held_flags: list[bool] = []
    before_publish = True
    for node in cls.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        scan = _LockedCallScan(is_lock, "_record_push")
        scan.walk_function(node)
        held_flags.extend(scan.calls)
        # dedup-before-publish: a method that records AND publishes must
        # read self._applied_push before its first publish store
        if not scan.calls:
            continue
        publish_line = None
        check_line = None
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if _is_self_attr(t, "state") or _is_self_attr(t, "_pub"):
                        publish_line = min(
                            publish_line or sub.lineno, sub.lineno
                        )
            if (
                isinstance(sub, ast.Attribute)
                and sub.attr == "_applied_push"
                and isinstance(sub.ctx, ast.Load)
            ):
                check_line = min(check_line or sub.lineno, sub.lineno)
        if publish_line is not None and (
            check_line is None or check_line > publish_line
        ):
            before_publish = False
    out["ledger_record_under_apply_lock"] = (
        bool(held_flags) and all(held_flags)
    )
    out["ledger_checked_before_apply"] = before_publish and bool(held_flags)
    return out


def _rcu_tables(cls: ast.ClassDef) -> dict[str, Any]:
    sites: set[str] = set()
    bump = False
    for node in cls.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stores_pub = any(
            isinstance(sub, ast.Assign)
            and any(_is_self_attr(t, "_pub") for t in sub.targets)
            for sub in ast.walk(node)
        )
        if not stores_pub:
            continue
        if node.name != "__init__":
            sites.add(node.name)
            # version bump: the new tuple derives from _pub[1] + 1
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.BinOp)
                    and isinstance(sub.op, ast.Add)
                    and isinstance(sub.right, ast.Constant)
                    and sub.right.value == 1
                    and any(
                        isinstance(x, ast.Attribute) and x.attr == "_pub"
                        for x in ast.walk(sub.left)
                    )
                ):
                    bump = True
    return {
        "publish_sites": frozenset(sites),
        "publish_bumps_version": bump,
    }


def _ssp_tables(cls: ast.ClassDef) -> dict[str, Any]:
    delegates = False
    for node in cls.body:
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == "retire"
        ):
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Call)
                    and _is_self_attr(sub.func, "finish")
                    and any(
                        isinstance(a, ast.Attribute) and a.attr == "RETIRED"
                        for a in sub.args
                    )
                ):
                    delegates = True
    return {"retire_delegates_to_finish": delegates}


def derive_code_tables(index: PackageIndex) -> dict[str, Any]:
    """The code-side facts, derived per subsystem PRESENT in the tree
    (absent subsystems contribute no keys — snippet indexes exercise
    one table at a time; the real package derives all of them)."""
    out: dict[str, Any] = {}
    ledger = _find_class(
        index, lambda c: _defines_method(c, "_record_push")
    )
    if ledger is not None:
        relpath, cls = ledger
        out["__ledger_site__"] = (relpath, cls.lineno, cls.name)
        out.update(_ledger_tables(index, relpath, cls))
    rcu_cls = _find_class(
        index,
        lambda c: any(
            isinstance(sub, ast.Assign)
            and any(_is_self_attr(t, "_pub") for t in sub.targets)
            for sub in ast.walk(c)
        ),
    )
    if rcu_cls is not None:
        relpath, cls = rcu_cls
        out["__rcu_site__"] = (relpath, cls.lineno, cls.name)
        out.update(_rcu_tables(cls))
    clock = _find_class(
        index,
        lambda c: _defines_method(c, "retire")
        and _defines_method(c, "finish"),
    )
    if clock is not None:
        relpath, cls = clock
        out["__ssp_site__"] = (relpath, cls.lineno, cls.name)
        out.update(_ssp_tables(cls))
    return out


def _site_for(key: str, tables: dict[str, Any]) -> tuple[str, int, str]:
    if key in _RCU_KEYS:
        return tables["__rcu_site__"]
    if key in _SSP_KEYS:
        return tables["__ssp_site__"]
    return tables["__ledger_site__"]


def conformance_diff(index: PackageIndex) -> list[Finding]:
    """One finding per spec assumption the derived code tables
    contradict. Empty on the real package — the acceptance bar."""
    from parameter_server_tpu.analysis.specs import SPECS

    tables = derive_code_tables(index)
    out: list[Finding] = []
    for spec_name, mod in SPECS.items():
        for key, want in mod.ASSUMPTIONS.items():
            if key not in tables:
                continue  # subsystem absent from this tree: not judged
            got = tables[key]
            if got == want:
                continue
            relpath, line, cls_name = _site_for(key, tables)
            want_s = (
                "{" + ", ".join(sorted(want)) + "}"
                if isinstance(want, frozenset) else repr(want)
            )
            got_s = (
                "{" + ", ".join(sorted(got)) + "}"
                if isinstance(got, frozenset) else repr(got)
            )
            out.append(Finding(
                "spec-conformance", relpath, line,
                f"spec {spec_name!r} assumes {key} = {want_s} but "
                f"{cls_name} derives {got_s} — the model and the code "
                "have drifted; change analysis/specs/ WITH this code "
                "(reviewed together) or the checked protocol no longer "
                "describes what ships",
            ))
    return out


def check_spec_conformance(index: PackageIndex) -> list[Finding]:
    return conformance_diff(index)


def check_model_invariants(index: PackageIndex) -> list[Finding]:
    """Run the tier-1-bounded model suite inside lint: a spec edit (or
    bound change) that makes a protocol model violate its invariants —
    or stop exhausting its bounded space — fails the same gate the
    code-side checkers do. Skipped for snippet indexes (the models are
    package facts, not snippet facts)."""
    if index.get("parallel/multislice.py") is None:
        return []
    from parameter_server_tpu.analysis.model import check
    from parameter_server_tpu.analysis.specs import SPECS

    out: list[Finding] = []
    for name, mod in sorted(SPECS.items()):
        res = check(mod.tier1(), max_states=120_000)
        rel = f"analysis/specs/{mod.__name__.rsplit('.', 1)[-1]}.py"
        if res.violation is not None:
            out.append(Finding(
                "model-invariants", rel, 1,
                f"spec {name!r} violates its own "
                f"{res.violation.kind} at tier-1 bounds: "
                f"{res.violation.message} (trace: "
                + " -> ".join(res.violation.trace[-6:]) + ")",
            ))
        elif not res.complete:
            out.append(Finding(
                "model-invariants", rel, 1,
                f"spec {name!r} no longer exhausts its tier-1 bounds "
                f"({res.states} states explored, cap hit) — 'verified' "
                "claims need a complete run; shrink the bounds or raise "
                "the cap",
            ))
    return out
