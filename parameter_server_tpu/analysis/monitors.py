"""Streaming protocol monitors: ONE detection automaton per invariant,
shared by the live audit plane and the postmortem plane.

psmc (analysis/model.py + analysis/specs/) proves the exactly-once /
RCU / SSP protocols over bounded models, and ``cli postmortem`` flags
their violations in the wreckage after the fact. This module is the
third leg (ISSUE 14): the SAME invariants as **incremental automata**
over a stream of flight-recorder events, cheap enough to run while the
cluster serves. Two feeders, one truth:

- **online** — ``utils/auditor.py`` at the coordinator feeds each
  node's heartbeat-piggybacked event batches as they arrive, with a
  watermark clock (``at`` = arrival time) deciding when an unpaired
  fact becomes a violation;
- **offline** — ``utils/postmortem.py`` feeds the merged black-box
  timeline (``at`` = event time) and calls :meth:`StreamMonitor.finish`
  at end-of-stream, so the postmortem's anomaly detectors ARE these
  monitors and the two planes cannot drift.

Event form (the postmortem timeline's normal form, plus feeder fields):
``{"ts": float, "life": hashable, "etype": str, "args": dict,
"at": float}``. ``life`` identifies one process life — ``(proc, pid)``
offline, the coordinator node id online; per-life invariants (RCU
monotonicity, heal convergence) key on it.

Every monitor declares:

- ``EVENTS`` — the etypes it consumes (a literal frozenset: the pslint
  ``flightrec-contract`` checker reads these statically, so a monitor's
  events count as "known to the diagnostic plane" package-wide);
- ``BUGS`` — seeded violation drills (the psmc ``BUGS`` pattern): each
  is a zero-arg callable returning ``(monitor, events, expected_kind)``
  such that feeding the events MUST produce a violation of that kind.
  The tier-1 mutation-coverage contract test fails if a registered
  monitor has none — a monitor that never demonstrated it can catch
  its own bug class is assumed blind.

This module is a dependency LEAF (stdlib only): the production auditor
and postmortem import it without dragging in the analyzer machinery.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Any, Callable

Violation = dict[str, Any]
Event = dict[str, Any]

#: end-of-stream sentinel for finish() (every watermark expires)
_END = float("inf")


def _ev(
    at: float, life: Any, etype: str, args: dict[str, Any]
) -> Event:
    """Build a normalized event (drills + feeders)."""
    return {"ts": at, "life": life, "etype": etype, "args": args, "at": at}


class StreamMonitor:
    """Base automaton: feed events, collect violations.

    ``feed`` consumes one normalized event (the feeder pre-filters on
    ``EVENTS``) and returns violations detectable immediately;
    ``flush(now)`` returns violations whose watermark expired (online
    cadence: the coordinator sweep); ``finish()`` is offline
    end-of-stream — everything still unpaired is judged."""

    name = "monitor"
    EVENTS: frozenset[str] = frozenset()
    BUGS: dict[str, Callable[[], tuple["StreamMonitor", list[Event], str]]] = {}

    def feed(self, ev: Event) -> list[Violation]:
        raise NotImplementedError

    def flush(self, now: float) -> list[Violation]:
        return []

    def finish(self) -> list[Violation]:
        return self.flush(_END)

    def _v(self, kind: str, **fields: Any) -> Violation:
        return {"kind": kind, "monitor": self.name, **fields}


def _pair_keys(args: dict[str, Any]) -> list[tuple[str, str]]:
    """Every (cid, seq) identity an apply event witnesses: the batch
    ``pairs`` list plus the serial path's direct cid/seq fields."""
    out: list[tuple[str, str]] = []
    for pair in args.get("pairs", ()):
        try:
            cid, seq = pair
        except (TypeError, ValueError):
            continue
        if cid is not None:
            out.append((str(cid), str(seq)))
    cid, seq = args.get("cid"), args.get("seq")
    if cid is not None and seq is not None:
        out.append((str(cid), str(seq)))
    return out


class AckAppliedMonitor(StreamMonitor):
    """ack => applied-exactly-once, within a watermark window.

    The wire's contract (psmc ``exactly_once`` spec, live): a client
    holding an ok push reply must have its (cid, seq) in some server's
    apply ledger (``apply.commit`` pairs / ``apply.replay`` dedup
    hits), and a (cid, seq) must never be COMMITTED twice. Pairing is
    order-free — ack-then-commit and commit-then-ack both resolve —
    and resolved identities are GC'd into a bounded recently-done LRU
    (so duplicate acks from wire chaos still match), which is what
    keeps the automaton's memory bounded on an infinite stream."""

    name = "ack-applied"
    EVENTS = frozenset({"rpc.reply", "apply.commit", "apply.replay"})

    #: resolved identities retained for duplicate-ack matching
    DONE_CAP = 8192

    def __init__(self, watermark_s: float = 15.0):
        self.watermark_s = float(watermark_s)
        self._applied: dict[tuple[str, str], float] = {}  # key -> at
        self._pending: dict[tuple[str, str], Event] = {}  # acked, unproven
        # resolved identities -> True if a COMMIT was witnessed, False
        # if resolved without one (flush-expired ack, GC'd): the
        # provenance decides whether a later commit is a double apply
        # or merely late
        self._done: OrderedDict[tuple[str, str], bool] = OrderedDict()

    def _resolve(self, key: tuple[str, str], committed: bool) -> None:
        self._done[key] = committed or self._done.get(key, False)
        self._done.move_to_end(key)
        while len(self._done) > self.DONE_CAP:
            self._done.popitem(last=False)

    def feed(self, ev: Event) -> list[Violation]:
        out: list[Violation] = []
        et = ev["etype"]
        if et in ("apply.commit", "apply.replay"):
            for key in _pair_keys(ev["args"]):
                committed_before = (
                    key in self._applied or self._done.get(key, False)
                )
                if et == "apply.commit" and committed_before:
                    # a replay is the dedup path doing its job; a SECOND
                    # commit of an already-committed identity — whether
                    # or not the ack pairing resolved it in between —
                    # is the exactly-once violation
                    out.append(self._v(
                        "double-applied", cid=key[0], seq=key[1],
                        life=ev["life"], ts=ev["ts"],
                    ))
                if key in self._pending:
                    del self._pending[key]
                    self._resolve(key, committed=True)
                elif committed_before or key in self._done:
                    self._resolve(key, committed=True)
                    self._applied.pop(key, None)
                else:
                    self._applied[key] = ev["at"]
        elif et == "rpc.reply":
            a = ev["args"]
            if a.get("cmd") != "push" or not a.get("ok", True):
                return out
            cid, seq = a.get("cid"), a.get("seq")
            if cid is None or seq is None:
                return out
            key = (str(cid), str(seq))
            if key in self._applied:
                del self._applied[key]
                self._resolve(key, committed=True)
            elif key not in self._done:
                self._pending[key] = ev
        return out

    def flush(self, now: float) -> list[Violation]:
        out: list[Violation] = []
        for key in [
            k for k, e in self._pending.items()
            if now - e["at"] > self.watermark_s
        ]:
            e = self._pending.pop(key)
            out.append(self._v(
                "acked-but-unapplied", cid=key[0], seq=key[1],
                ack_ts=e["ts"], life=e["life"],
            ))
            # judged once; a LATE commit won't re-flag the ack — but
            # committed=False keeps the provenance honest, so it won't
            # read as a double apply either
            self._resolve(key, committed=False)
        # commits whose acks never came (client died / ack spool hole):
        # GC after a generous multiple of the pairing window
        horizon = 4 * self.watermark_s
        for key in [
            k for k, at in self._applied.items() if now - at > horizon
        ]:
            del self._applied[key]
            self._resolve(key, committed=True)
        return out


def _bug_ack_without_apply():
    m = AckAppliedMonitor(watermark_s=5.0)
    evs = [_ev(0.0, "worker-0", "rpc.reply",
               {"cmd": "push", "cid": "c1", "seq": "k0", "ok": True})]
    return m, evs, "acked-but-unapplied"


def _bug_double_apply():
    m = AckAppliedMonitor(watermark_s=5.0)
    evs = [
        _ev(0.0, "server-0", "apply.commit",
            {"ver": 2, "pairs": [["c1", "k0"]]}),
        _ev(0.1, "server-0", "apply.commit",
            {"ver": 3, "pairs": [["c1", "k0"]]}),
    ]
    return m, evs, "double-applied"


def _bug_double_apply_after_ack():
    # the COMMON live ordering: the identity is already ack-resolved
    # when the second commit lands — provenance in the done-LRU must
    # still convict it
    m = AckAppliedMonitor(watermark_s=5.0)
    evs = [
        _ev(0.0, "server-0", "apply.commit",
            {"ver": 2, "pairs": [["c1", "k0"]]}),
        _ev(0.1, "worker-0", "rpc.reply",
            {"cmd": "push", "cid": "c1", "seq": "k0", "ok": True}),
        _ev(0.2, "server-0", "apply.commit",
            {"ver": 3, "pairs": [["c1", "k0"]]}),
    ]
    return m, evs, "double-applied"


AckAppliedMonitor.BUGS = {
    "ack-without-apply": _bug_ack_without_apply,
    "double-apply": _bug_double_apply,
    "double-apply-after-ack": _bug_double_apply_after_ack,
}


class RcuMonitor(StreamMonitor):
    """Per-life RCU snapshot-version monotonicity.

    Every ``rcu.publish`` bumps an opaque version whose high 40+ bits
    are a per-server-life nonce (ShardServer's 23-nonce/40-counter
    layout); within one (life, nonce) stream the version is strictly
    increasing — a decrease is a rollback or a torn publish, the
    failure class the psmc ``rcu`` spec models. Keying on the nonce as
    well as the life means two server instances sharing one process
    (or one node id) can never false-positive against each other."""

    name = "rcu-version"
    EVENTS = frozenset({"rcu.publish"})

    #: the version layout's counter width (multislice.ShardServer)
    NONCE_SHIFT = 40

    def __init__(self) -> None:
        self._last: dict[tuple[Any, int], int] = {}

    def feed(self, ev: Event) -> list[Violation]:
        ver = ev["args"].get("ver")
        if ver is None:
            return []
        v = int(ver)
        key = (ev["life"], v >> self.NONCE_SHIFT)
        prev = self._last.get(key)
        self._last[key] = v
        if prev is not None and v < prev:
            return [self._v(
                "version-regression", life=ev["life"],
                **{"from": prev, "to": v}, ts=ev["ts"],
            )]
        return []


def _bug_rcu_rollback():
    m = RcuMonitor()
    evs = [
        _ev(0.0, "server-0", "rcu.publish", {"ver": 101}),
        _ev(0.1, "server-0", "rcu.publish", {"ver": 99}),
    ]
    return m, evs, "version-regression"


RcuMonitor.BUGS = {"rcu-rollback": _bug_rcu_rollback}


class SspMonitor(StreamMonitor):
    """SSP bounded-staleness: a granted gate pass must respect tau.

    Mirrors SSPClock's gate (``wait(w, step)`` grants only when every
    non-retired worker has finished ``step - max_delay - 1``): replays
    ``ssp.finish`` / ``ssp.retire`` into a per-worker finished table
    and checks every GRANTED ``ssp.wait`` against it. A grant that
    outruns the bound is parked as a suspect first — the clock records
    its events outside its lock, so the enabling finish can land in
    the stream a moment late — and becomes a violation only when no
    justifying finish arrives within the grace window. Without a known
    ``max_delay`` (offline dumps don't carry it) the monitor is
    dormant; the coordinator learns the bound from ``ssp_init``."""

    name = "ssp-staleness"
    EVENTS = frozenset({"ssp.wait", "ssp.finish", "ssp.retire"})

    RETIRED = 1 << 60

    def __init__(
        self,
        max_delay: int | None = None,
        num_workers: int | None = None,
        grace_s: float = 5.0,
    ):
        self.max_delay = max_delay
        self.grace_s = float(grace_s)
        self._finished: dict[int, int] = {}
        if num_workers:
            self._finished = {w: -1 for w in range(int(num_workers))}
        self._suspects: list[dict[str, Any]] = []

    def set_bounds(self, max_delay: int, num_workers: int) -> None:
        self.max_delay = int(max_delay)
        for w in range(int(num_workers)):
            self._finished.setdefault(w, -1)

    def _min_finished(self) -> int:
        return min(self._finished.values()) if self._finished else -1

    def _recheck(self) -> None:
        mf = self._min_finished()
        self._suspects = [s for s in self._suspects if s["target"] > mf]

    def feed(self, ev: Event) -> list[Violation]:
        a = ev["args"]
        et = ev["etype"]
        if et == "ssp.finish":
            w, s = int(a["worker"]), int(a["step"])
            if s > self._finished.get(w, -1):
                self._finished[w] = s
                self._recheck()
        elif et == "ssp.retire":
            self._finished[int(a["worker"])] = self.RETIRED
            self._recheck()
        elif et == "ssp.wait":
            if self.max_delay is None or self.max_delay < 0:
                return []
            if not a.get("granted", True):
                return []
            w, step = int(a["worker"]), int(a["step"])
            self._finished.setdefault(w, -1)
            target = step - self.max_delay - 1
            if self._min_finished() < target:
                self._suspects.append({
                    "worker": w, "step": step, "target": target,
                    "at": ev["at"], "ts": ev["ts"], "life": ev["life"],
                })
        return []

    def flush(self, now: float) -> list[Violation]:
        out: list[Violation] = []
        keep: list[dict[str, Any]] = []
        mf = self._min_finished()
        for s in self._suspects:
            if s["target"] <= mf:
                continue  # justified since parking
            if now - s["at"] > self.grace_s:
                out.append(self._v(
                    "ssp-staleness", worker=s["worker"], step=s["step"],
                    min_finished=mf, max_delay=self.max_delay,
                    life=s["life"], ts=s["ts"],
                ))
            else:
                keep.append(s)
        self._suspects = keep
        return out


def _bug_ssp_overrun():
    m = SspMonitor(max_delay=1, num_workers=2, grace_s=1.0)
    evs = [
        _ev(0.0, "coord", "ssp.finish", {"worker": 0, "step": 9}),
        # worker 1 never finished anything, yet worker 0's step-9 grant
        # needs min_finished >= 7 — the clock should have parked it
        _ev(0.1, "coord", "ssp.wait",
            {"worker": 0, "step": 9, "granted": True}),
    ]
    return m, evs, "ssp-staleness"


SspMonitor.BUGS = {"staleness-overrun": _bug_ssp_overrun}


class HealMonitor(StreamMonitor):
    """Reconnect-without-heal, per life.

    A ``rpc.heal.begin`` that neither lands (``rpc.healed``) nor is
    outnumbered by later heals within the timeout means a peer died
    (or a partition held) and the client's window is parked — the
    postmortem's reconnect-without-heal flag, evaluated live. One
    violation per un-healed episode: the flag re-arms only after heals
    catch back up with begins."""

    name = "heal-convergence"
    EVENTS = frozenset({"rpc.heal.begin", "rpc.healed", "rpc.heal.failed"})

    def __init__(self, heal_timeout_s: float = 30.0):
        self.heal_timeout_s = float(heal_timeout_s)
        self._lives: dict[Any, dict[str, Any]] = {}

    def _life(self, life: Any) -> dict[str, Any]:
        st = self._lives.get(life)
        if st is None:
            st = self._lives[life] = {
                "begun": 0, "healed": 0, "failed": 0,
                "pending": deque(), "reported": False,
            }
        return st

    def feed(self, ev: Event) -> list[Violation]:
        st = self._life(ev["life"])
        et = ev["etype"]
        if et == "rpc.heal.begin":
            st["begun"] += 1
            st["pending"].append(ev["at"])
        elif et == "rpc.healed":
            st["healed"] += 1
            if st["pending"]:
                st["pending"].popleft()
            if not st["pending"]:
                st["reported"] = False  # converged: re-arm the episode
        elif et == "rpc.heal.failed":
            st["failed"] += 1
        return []

    def flush(self, now: float) -> list[Violation]:
        out: list[Violation] = []
        for life, st in self._lives.items():
            if st["reported"] or not st["pending"]:
                continue
            if now - st["pending"][0] > self.heal_timeout_s:
                st["reported"] = True
                out.append(self._v(
                    "reconnect-without-heal", life=life,
                    begun=st["begun"], healed=st["healed"],
                    failed=st["failed"],
                ))
        return out


def _bug_unhealed_reconnect():
    m = HealMonitor(heal_timeout_s=1.0)
    evs = [
        _ev(0.0, "worker-0", "rpc.heal.begin", {"addr": "a", "cid": "c1"}),
        _ev(0.5, "worker-0", "rpc.heal.failed", {"addr": "a", "cid": "c1"}),
    ]
    return m, evs, "reconnect-without-heal"


HealMonitor.BUGS = {"unhealed-reconnect": _bug_unhealed_reconnect}


class ShedStormMonitor(StreamMonitor):
    """Shed storms: admission control firing in bursts.

    ``serve.shed`` is healthy back-pressure one at a time and an
    overload incident in bursts — >= ``n`` sheds inside ``window_s``
    (event time, cluster-wide) fires once per storm; a quiet gap
    longer than the window re-arms it. The window is ORDER-TOLERANT:
    the live feeder delivers per-node streams in arrival order, so
    beat skew can interleave one node's older event timestamps after
    another's newer ones — entries are kept sorted (bisect) and the
    verdict is "some window_s span held >= n sheds", whatever order
    the evidence arrived in."""

    name = "shed-storm"
    EVENTS = frozenset({"serve.shed"})

    def __init__(self, n: int = 10, window_s: float = 1.0):
        self.n = max(int(n), 1)
        self.window_s = float(window_s)
        self._ts: list[float] = []  # sorted event times
        self._in_storm = False

    def feed(self, ev: Event) -> list[Violation]:
        import bisect

        ts = ev["ts"]
        newest = self._ts[-1] if self._ts else None
        if newest is not None and ts - newest > self.window_s:
            # a quiet gap longer than the window: the storm (if any)
            # ended — re-arm
            self._ts.clear()
            self._in_storm = False
        bisect.insort(self._ts, ts)
        newest = self._ts[-1]
        # trim everything that can no longer participate in ANY window
        # reaching the newest evidence
        lo = bisect.bisect_left(self._ts, newest - self.window_s)
        del self._ts[:lo]
        if len(self._ts) >= self.n and not self._in_storm:
            self._in_storm = True
            return [self._v(
                "shed-storm", count=len(self._ts),
                window_s=self.window_s, ts=self._ts[0],
                life=ev["life"],  # the shed that tipped the window
            )]
        return []


def _bug_shed_storm():
    m = ShedStormMonitor(n=10, window_s=1.0)
    evs = [
        _ev(1.0 + i * 0.01, "server-0", "serve.shed", {"sig": "s"})
        for i in range(12)
    ]
    return m, evs, "shed-storm"


ShedStormMonitor.BUGS = {"shed-storm": _bug_shed_storm}


# -- registry ---------------------------------------------------------------

#: every registered streaming monitor — the auditor instantiates all of
#: them, the postmortem feeds them offline, the mutation-coverage
#: contract test requires each to carry >= 1 seeded BUGS drill, and the
#: pslint flightrec-contract checker reads their EVENTS sets statically
MONITORS: dict[str, type[StreamMonitor]] = {
    AckAppliedMonitor.name: AckAppliedMonitor,
    RcuMonitor.name: RcuMonitor,
    SspMonitor.name: SspMonitor,
    HealMonitor.name: HealMonitor,
    ShedStormMonitor.name: ShedStormMonitor,
}


def monitor_events() -> frozenset[str]:
    """Union of every registered monitor's consumed etypes."""
    out: set[str] = set()
    for cls in MONITORS.values():
        out |= cls.EVENTS
    return frozenset(out)


def make_monitors(
    watermark_s: float = 15.0,
    heal_timeout_s: float = 30.0,
    shed_storm_n: int = 10,
    shed_storm_window_s: float = 1.0,
    ssp_max_delay: int | None = None,
    ssp_num_workers: int | None = None,
) -> list[StreamMonitor]:
    """One live instance of every registered monitor, bounds applied."""
    return [
        AckAppliedMonitor(watermark_s=watermark_s),
        RcuMonitor(),
        SspMonitor(max_delay=ssp_max_delay, num_workers=ssp_num_workers),
        HealMonitor(heal_timeout_s=heal_timeout_s),
        ShedStormMonitor(n=shed_storm_n, window_s=shed_storm_window_s),
    ]


def run_bug(
    cls: type[StreamMonitor], bug: str
) -> tuple[list[Violation], str]:
    """Run one seeded drill: returns (violations, expected_kind). The
    mutation-coverage contract asserts a violation of the expected kind
    is among them — a drill a monitor cannot catch fails the build."""
    monitor, events, expected = cls.BUGS[bug]()
    out: list[Violation] = []
    for ev in events:
        if ev["etype"] in cls.EVENTS:
            out += monitor.feed(ev)
    out += monitor.finish()
    return out, expected
