"""Flow-sensitive alias/provenance dataflow for the v2 checkers.

The PR-5 checkers were *syntactic*: they matched idioms (a ``with`` over
a lock-typed attribute, a literal counter name) and folded one boolean
fact through the call graph. The RCU and wire-protocol invariants need
*provenance*: "does this name alias the published RCU snapshot?",
"did this reply dict flow through ``decorated()``?" — facts that travel
through assignments, tuple unpacking, subscripts and helper calls.

This module is that engine. It is deliberately a TAG dataflow, not a
points-to analysis: every expression evaluates to a ``frozenset[str]``
of provenance tags, assignments propagate tags into a per-function
environment, statements are walked in order (flow-sensitive), branches
merge by union (may-alias), and a fixpoint over the package computes
two interprocedural summaries per function through the SAME call edges
``callgraph.py`` already resolves (self-methods, known-instance
attributes, constructors, module aliases):

- ``ret``: the tags a call to this function may return, with
  ``param:<i>`` pseudo-tags substituted by the caller's argument tags
  (so an identity helper is transparent to provenance);
- ``mutated_params``: argument positions the function may mutate
  (subscript-store, del, augmented assign, or a mutating method like
  ``.update``/``.pop``), so passing a tagged value to a mutating callee
  is observable at the call site.

Checkers drive it through a :class:`FlowPolicy`: ``seed`` introduces
tags at source expressions, ``element``/``call_result`` shape
propagation, and ``on_mutation``/``on_load``/``on_call`` observe the
facts. The walker also tracks the held-lock stack (the same ``with``
discipline ``HeldLockWalker`` walks) so a policy can condition a rule
on "under a lock" — the RCU raw-attribute rule needs exactly that.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Callable

from parameter_server_tpu.analysis.callgraph import CallGraph, OwnerKey
from parameter_server_tpu.analysis.core import PackageIndex, iter_functions

Tags = frozenset[str]
EMPTY: Tags = frozenset()

#: methods that mutate their receiver in place (dict/list/set/ndarray)
MUTATING_METHODS = frozenset({
    "update", "pop", "popitem", "clear", "setdefault", "__setitem__",
    "append", "extend", "insert", "remove", "sort", "fill", "resize",
})

#: methods that return a view/iterator still aliasing the receiver's
#: contents (mutating what they yield mutates the receiver)
ACCESSOR_METHODS = frozenset({"items", "values", "get", "keys", "move_to_end"})

#: calls that return a FRESH container/buffer — provenance does not
#: survive them (np.array always copies; dict()/list() shallow-copy the
#: container itself, which is the alias the mutation checkers track)
FRESH_CALLS = frozenset({"dict", "list", "set", "tuple", "sorted", "copy",
                         "deepcopy", "array"})


def param_tag(i: int) -> str:
    return f"param:{i}"


def is_param_tag(t: str) -> bool:
    return t.startswith("param:")


@dataclass
class Summary:
    """Interprocedural facts for one function."""

    ret: Tags = EMPTY
    mutated_params: frozenset[int] = frozenset()

    def key(self) -> tuple:
        return (self.ret, self.mutated_params)


class FlowPolicy:
    """Checker-supplied semantics for the generic walker. Every hook has
    a conservative default; override what the invariant needs."""

    #: receiver methods treated as in-place mutation of a tagged value
    mutating_methods: frozenset[str] = MUTATING_METHODS

    def owns(self, tag: str) -> bool:
        """Whether ``tag`` belongs to this policy's namespace. Policies
        composed into one shared run (:class:`CompositePolicy`) must
        keep disjoint namespaces (``rcu*``, ``decorated``, ``u:*``,
        ``ck:*``, ``id:*``) and claim ONLY theirs, so a units tag never
        leaks into the RCU policy's element/call_result shaping."""
        return True

    def begin_function(
        self, relpath: str, cls_name: str | None, fn_name: str
    ) -> None:
        """Called before each function's walk (both passes) so a policy
        can anchor its findings without inferring position from seeds."""

    def seed(
        self, expr: ast.expr, cls_name: str | None, relpath: str
    ) -> Tags:
        """Source tags for a load of ``expr`` (attribute reads etc.)."""
        return EMPTY

    def element(self, tags: Tags, index: object) -> Tags:
        """Tags of one element read out of a tagged value (subscript
        read, tuple destructure position, attribute read, iteration).
        ``index`` is an int for destructure positions, the attribute
        name for attribute reads, or None. Default: provenance sticks
        to what a container yields (a row of a published table is still
        published state)."""
        return frozenset(t for t in tags if not is_param_tag(t))

    def call_result(
        self, call: ast.Call, recv_tags: Tags, arg_tags: list[Tags]
    ) -> Tags:
        """Tags of a call result the summaries could not resolve.
        ``recv_tags`` are the tags of ``X`` in ``X.m(...)`` (EMPTY for
        plain calls). Default: accessor methods keep the receiver's
        provenance, everything else is fresh."""
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr in ACCESSOR_METHODS:
            return self.element(recv_tags, fn.attr)
        return EMPTY

    def on_mutation(
        self, node: ast.AST, kind: str, tags: Tags,
        held: list[tuple[str, str, int]], desc: str,
    ) -> None:
        """A mutation (``kind`` in setitem/setattr/del/augassign/call/
        callee) observed on a value carrying ``tags``."""

    def on_load(
        self, expr: ast.expr, cls_name: str | None,
        held: list[tuple[str, str, int]], fn_name: str,
    ) -> None:
        """Every attribute/name load, with the held-lock stack (the RCU
        raw-attribute rule hooks here)."""

    def on_call(
        self, call: ast.Call, arg_tags: list[Tags],
        held: list[tuple[str, str, int]],
        eval_expr: Callable[[ast.expr], Tags],
    ) -> None:
        """Every call site, after argument evaluation."""

    # -- quantity-flow hooks (ISSUE 20) -------------------------------------
    # binop/unary return RESULT tags and are called in BOTH passes (the
    # fixpoint needs them to propagate); any finding they record must be
    # gated on ``report`` — pass-1 tags are still growing, so a
    # "disjoint units" verdict before the fixpoint can be transiently
    # wrong. The on_* observers below are only called in the report
    # pass, so they may record findings unconditionally.

    def binop(
        self, node: ast.AST, op: ast.operator, ltags: Tags, rtags: Tags,
        report: bool,
    ) -> Tags:
        """Tags of ``l <op> r`` (also driven for ``AugAssign``, with the
        statement as ``node``). Default: arithmetic yields fresh values
        (the pre-v3 behavior)."""
        return EMPTY

    def unary(
        self, node: ast.UnaryOp, op: ast.unaryop, tags: Tags, report: bool
    ) -> Tags:
        """Tags of ``<op> x``. Default: fresh."""
        return EMPTY

    def on_compare(self, node: ast.Compare, operand_tags: list[Tags]) -> None:
        """A comparison chain, with the tags of ``[left, *comparators]``
        (``node.ops`` carries the operators)."""

    def on_bind(self, name: str, tags: Tags, stmt: ast.stmt) -> None:
        """A value carrying ``tags`` bound to local/global NAME ``name``
        (plain assignment targets; ``stmt.value`` is the source when the
        statement has one)."""

    def on_store(
        self, kind: str, name: str, tags: Tags, stmt: ast.stmt
    ) -> None:
        """A value carrying ``tags`` stored into an attribute
        (``kind="attr"``) or a constant-string subscript slot
        (``kind="key"``) named ``name`` — the sink side of the units
        suffix rules (wire header slots, config keys)."""

    def on_keyword(self, call: ast.Call, kw_name: str, tags: Tags) -> None:
        """A keyword argument ``kw_name=<value carrying tags>`` at a
        call site (named-parameter sink check)."""

    def finish_call(self, call: ast.Call, tags: Tags) -> Tags:
        """Last word on a call's result tags, applied on EVERY path
        (summary-resolved, fresh, and ``call_result``). This is where a
        declared conversion function overrides even a resolved callee's
        summary — ``to_ms(x)`` returns ms because the whitelist says so,
        whatever its body's tags computed. Default: identity."""
        return tags


@dataclass
class _FnCtx:
    relpath: str
    cls_name: str | None
    fndef: ast.FunctionDef | ast.AsyncFunctionDef
    owner: OwnerKey


class FlowWalker:
    """One function's flow-sensitive walk. Not reusable across calls."""

    def __init__(
        self,
        policy: FlowPolicy,
        graph: CallGraph,
        ctx: _FnCtx,
        summaries: dict[OwnerKey, Summary],
        is_lock_expr: Callable[[ast.expr], str | None],
        report: bool,
    ):
        self._p = policy
        self._g = graph
        self._ctx = ctx
        self._summaries = summaries
        self._is_lock = is_lock_expr
        self._report = report  # False during the summary fixpoint
        self.env: dict[str, Tags] = {}
        self.held: list[tuple[str, str, int]] = []
        self.ret_tags: Tags = EMPTY
        self.mutated_params: set[int] = set()
        self._param_names: dict[str, int] = {}

    # -- entry -------------------------------------------------------------

    def run(self) -> Summary:
        fndef = self._ctx.fndef
        args = fndef.args
        names = [a.arg for a in args.posonlyargs + args.args]
        # param indices are numbered EXCLUDING self, so they line up
        # with call.args at every call site this graph resolves — both
        # `mod.fn(a)` and bound `self.m(a)` pass the first real param
        # as args[0] (the receiver never rides the arg list)
        idx = 0
        for n in names:
            if n == "self":
                continue
            self._param_names[n] = idx
            self.env[n] = frozenset({param_tag(idx)})
            idx += 1
        self._walk_body(fndef.body)
        return Summary(self.ret_tags, frozenset(self.mutated_params))

    # -- statements --------------------------------------------------------

    def _walk_body(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self._walk_stmt(stmt)

    def _walk_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs run later, under their own walk
        if isinstance(stmt, ast.Assign):
            tags = self._eval(stmt.value)
            for t in stmt.targets:
                self._assign(t, tags, stmt)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._assign(stmt.target, self._eval(stmt.value), stmt)
            return
        if isinstance(stmt, ast.AugAssign):
            vtags = self._eval(stmt.value)
            t = stmt.target
            if isinstance(t, ast.Name):
                # the target is a load+store: seed it so `total_ms +=
                # dur_us` sees the suffix tag even on first write
                cur = self.env.get(t.id, EMPTY) | self._p.seed(
                    t, self._ctx.cls_name, self._ctx.relpath
                )
                self._mutation(stmt, "augassign", cur, ast.unparse(t))
                self._p.binop(stmt, stmt.op, cur, vtags, self._report)
                self.env[t.id] = cur | vtags
            elif isinstance(t, (ast.Subscript, ast.Attribute)):
                base = self._eval(t.value)
                tgt = self._p.seed(t, self._ctx.cls_name, self._ctx.relpath)
                self._p.binop(stmt, stmt.op, tgt, vtags, self._report)
                self._mutation(stmt, "augassign", base, ast.unparse(t))
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)):
                    base = self._eval(t.value)
                    self._mutation(stmt, "del", base, ast.unparse(t))
                elif isinstance(t, ast.Name):
                    self.env.pop(t.id, None)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.ret_tags = self.ret_tags | self._eval(stmt.value)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in stmt.items:
                tags = self._eval(item.context_expr)
                key = self._is_lock(item.context_expr)
                if key is not None:
                    self.held.append(
                        (key, ast.unparse(item.context_expr), stmt.lineno)
                    )
                    pushed += 1
                if item.optional_vars is not None:
                    self._assign(item.optional_vars, tags, stmt)
            self._walk_body(stmt.body)
            for _ in range(pushed):
                self.held.pop()
            return
        if isinstance(stmt, ast.If):
            self._eval(stmt.test)
            self._branch([stmt.body, stmt.orelse])
            return
        if isinstance(stmt, (ast.While,)):
            self._eval(stmt.test)
            self._loop_body(stmt.body)
            self._branch([stmt.orelse])
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = self._eval(stmt.iter)
            self._assign(stmt.target, self._p.element(it, "iter"), stmt)
            self._loop_body(stmt.body)
            self._branch([stmt.orelse])
            return
        if isinstance(stmt, ast.Try):
            self._walk_body(stmt.body)
            merged = dict(self.env)
            for h in stmt.handlers:
                self._walk_body(h.body)
                for k, v in self.env.items():
                    merged[k] = merged.get(k, EMPTY) | v
                self.env = dict(merged)
            self._walk_body(stmt.orelse)
            self._walk_body(stmt.finalbody)
            return
        if isinstance(stmt, (ast.Expr, ast.Assert, ast.Raise)):
            for sub in ast.iter_child_nodes(stmt):
                if isinstance(sub, ast.expr):
                    self._eval(sub)
            return
        # anything else (pass, global, import...): evaluate embedded
        # expressions so call/mutation hooks still observe them
        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, ast.expr):
                self._eval(sub)

    def _branch(self, bodies: list[list[ast.stmt]]) -> None:
        """Walk alternative bodies from the same entry env, union-merge
        the exits (may-alias join)."""
        entry = dict(self.env)
        merged = dict(self.env)
        for body in bodies:
            self.env = dict(entry)
            self._walk_body(body)
            for k, v in self.env.items():
                merged[k] = merged.get(k, EMPTY) | v
        self.env = merged

    def _loop_body(self, body: list[ast.stmt]) -> None:
        """Two passes so loop-carried tags reach their first use (tags
        only grow, so two monotone passes reach the fixpoint any
        assignment chain inside one body can build)."""
        self._branch([body])
        self._branch([body])

    # -- assignment / destructuring ----------------------------------------

    def _assign(self, target: ast.expr, tags: Tags, stmt: ast.stmt) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = tags
            if self._report:
                self._p.on_bind(target.id, tags, stmt)
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for i, elt in enumerate(target.elts):
                if isinstance(elt, ast.Starred):
                    self._assign(elt.value, self._p.element(tags, None), stmt)
                else:
                    self._assign(elt, self._p.element(tags, i), stmt)
            return
        if isinstance(target, ast.Subscript):
            base = self._eval(target.value)
            self._eval(target.slice)
            self._mutation(stmt, "setitem", base, ast.unparse(target))
            if self._report and isinstance(target.slice, ast.Constant) \
                    and isinstance(target.slice.value, str):
                self._p.on_store("key", target.slice.value, tags, stmt)
            return
        if isinstance(target, ast.Attribute):
            base = self._eval(target.value)
            self._mutation(stmt, "setattr", base, ast.unparse(target))
            if self._report:
                self._p.on_store("attr", target.attr, tags, stmt)
            return
        if isinstance(target, ast.Starred):
            self._assign(target.value, tags, stmt)

    def _mutation(
        self, node: ast.AST, kind: str, tags: Tags, desc: str
    ) -> None:
        for t in tags:
            if is_param_tag(t):
                self.mutated_params.add(int(t.split(":", 1)[1]))
        if self._report and tags:
            self._p.on_mutation(node, kind, tags, self.held, desc)

    # -- expressions -------------------------------------------------------

    def _eval(self, expr: ast.expr) -> Tags:
        p = self._p
        if isinstance(expr, ast.Name):
            tags = self.env.get(expr.id, EMPTY)
            seeded = p.seed(expr, self._ctx.cls_name, self._ctx.relpath)
            if self._report:
                p.on_load(
                    expr, self._ctx.cls_name, self.held,
                    self._ctx.fndef.name,
                )
            return tags | seeded
        if isinstance(expr, ast.Attribute):
            base = self._eval(expr.value)
            seeded = p.seed(expr, self._ctx.cls_name, self._ctx.relpath)
            if self._report:
                p.on_load(
                    expr, self._ctx.cls_name, self.held,
                    self._ctx.fndef.name,
                )
            return p.element(base, expr.attr) | seeded
        if isinstance(expr, ast.Subscript):
            base = self._eval(expr.value)
            idx: object = None
            if isinstance(expr.slice, ast.Constant):
                idx = expr.slice.value
            self._eval(expr.slice)
            seeded = p.seed(expr, self._ctx.cls_name, self._ctx.relpath)
            return p.element(base, idx) | seeded
        if isinstance(expr, ast.Call):
            return self._eval_call(expr)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            out = EMPTY
            for e in expr.elts:
                out |= self._eval(e)
            return out
        if isinstance(expr, ast.Dict):
            out = EMPTY
            for k in expr.keys:
                if k is not None:
                    self._eval(k)
            for v in expr.values:
                self._eval(v)
            return out  # fresh container; values' provenance not carried
        if isinstance(expr, ast.IfExp):
            self._eval(expr.test)
            return self._eval(expr.body) | self._eval(expr.orelse)
        if isinstance(expr, ast.BoolOp):
            out = EMPTY
            for v in expr.values:
                out |= self._eval(v)
            return out
        if isinstance(expr, ast.NamedExpr):
            tags = self._eval(expr.value)
            self._assign(expr.target, tags, expr)  # type: ignore[arg-type]
            return tags
        if isinstance(expr, ast.Starred):
            return self._eval(expr.value)
        if isinstance(expr, ast.Await):
            return self._eval(expr.value)
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            # comprehensions build fresh containers; still evaluate the
            # parts so call hooks observe them, binding iteration names
            for gen in expr.generators:
                it = self._eval(gen.iter)
                self._assign(gen.target, p.element(it, "iter"), expr)  # type: ignore[arg-type]
                for cond in gen.ifs:
                    self._eval(cond)
            if isinstance(expr, ast.DictComp):
                self._eval(expr.key)
                self._eval(expr.value)
            else:
                self._eval(expr.elt)
            return EMPTY
        if isinstance(expr, ast.BinOp):
            l = self._eval(expr.left)
            r = self._eval(expr.right)
            return p.binop(expr, expr.op, l, r, self._report)
        if isinstance(expr, ast.UnaryOp):
            t = self._eval(expr.operand)
            return p.unary(expr, expr.op, t, self._report)
        if isinstance(expr, ast.Compare):
            operand_tags = [self._eval(expr.left)]
            operand_tags.extend(self._eval(c) for c in expr.comparators)
            if self._report:
                p.on_compare(expr, operand_tags)
            return EMPTY  # booleans are unit-free
        if isinstance(expr, ast.Lambda):
            return EMPTY  # body runs later; out of intraprocedural scope
        # constants, f-strings, slices...
        for sub in ast.iter_child_nodes(expr):
            if isinstance(sub, ast.expr):
                self._eval(sub)
        return EMPTY

    def _eval_call(self, call: ast.Call) -> Tags:
        p = self._p
        fn = call.func
        recv_tags = EMPTY
        if isinstance(fn, ast.Attribute):
            recv_tags = self._eval(fn.value)
        arg_tags = [self._eval(a) for a in call.args]
        for kw in call.keywords:
            kw_tags = self._eval(kw.value)
            if self._report and kw.arg is not None:
                p.on_keyword(call, kw.arg, kw_tags)
        # receiver-mutating methods on a tagged value
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in p.mutating_methods
            and recv_tags
        ):
            self._mutation(call, "call", recv_tags,
                           f"{ast.unparse(fn)}(...)")
        if self._report:
            p.on_call(call, arg_tags, self.held, self._eval)
        # resolve through the call graph summaries
        out = EMPTY
        resolved = False
        for callee in self._g.callees(
            self._ctx.relpath, self._ctx.cls_name, call
        ):
            s = self._summaries.get(callee)
            if s is None:
                continue
            resolved = True
            # substitute param pseudo-tags with the caller's arg tags
            for t in s.ret:
                if is_param_tag(t):
                    i = int(t.split(":", 1)[1])
                    if i < len(arg_tags):
                        out |= arg_tags[i]
                else:
                    out |= frozenset({t})
            for i in s.mutated_params:
                if i < len(arg_tags) and arg_tags[i]:
                    self._mutation(
                        call, "callee", arg_tags[i],
                        f"{ast.unparse(fn)}(...) arg {i}",
                    )
        if not resolved:
            if (isinstance(fn, ast.Name) and fn.id in FRESH_CALLS) or (
                isinstance(fn, ast.Attribute) and fn.attr in FRESH_CALLS
            ):
                out = EMPTY
            else:
                out = p.call_result(call, recv_tags, arg_tags)
        return p.finish_call(call, out)


class DataflowAnalysis:
    """Package-wide driver: computes the interprocedural summaries to a
    fixpoint, then replays every function with reporting enabled so the
    policy's hooks observe the final facts."""

    def __init__(
        self,
        index: PackageIndex,
        policy: FlowPolicy,
        graph: CallGraph | None = None,
    ):
        self.index = index
        self.policy = policy
        self.graph = graph or CallGraph(index)
        self.summaries: dict[OwnerKey, Summary] = {}
        self._bodies: list[_FnCtx] = []
        for f in index.files:
            for cls_name, fndef in iter_functions(f.tree):
                owner: OwnerKey = (
                    ("m", cls_name, fndef.name)
                    if cls_name is not None
                    else ("f", f.relpath, fndef.name)
                )
                self._bodies.append(_FnCtx(f.relpath, cls_name, fndef, owner))

    def _lock_key_fn(self, ctx: _FnCtx) -> Callable[[ast.expr], str | None]:
        g = self.graph

        def key(expr: ast.expr) -> str | None:
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and ctx.cls_name is not None
            ):
                return g.lock_attr_key(ctx.cls_name, expr.attr)
            if isinstance(expr, ast.Name):
                return g.module_locks.get(expr.id)
            return None

        return key

    def run(self, max_rounds: int = 8) -> None:
        # pass 1: summaries to fixpoint (reporting off — a finding must
        # not fire once per fixpoint round)
        for _ in range(max_rounds):
            changed = False
            for ctx in self._bodies:
                self.policy.begin_function(
                    ctx.relpath, ctx.cls_name, ctx.fndef.name
                )
                w = FlowWalker(
                    self.policy, self.graph, ctx, self.summaries,
                    self._lock_key_fn(ctx), report=False,
                )
                s = w.run()
                old = self.summaries.get(ctx.owner)
                if old is None or old.key() != s.key():
                    # merge (owner keys can collide across same-named
                    # classes; union is the sound direction)
                    if old is not None:
                        s = Summary(
                            old.ret | s.ret,
                            old.mutated_params | s.mutated_params,
                        )
                    self.summaries[ctx.owner] = s
                    changed = True
            if not changed:
                break
        # pass 2: replay with the policy observing
        for ctx in self._bodies:
            self.policy.begin_function(
                ctx.relpath, ctx.cls_name, ctx.fndef.name
            )
            FlowWalker(
                self.policy, self.graph, ctx, self.summaries,
                self._lock_key_fn(ctx), report=True,
            ).run()


class CompositePolicy(FlowPolicy):
    """Fan one walk out to several policies with disjoint tag
    namespaces — the shared-fixpoint optimization (ISSUE 20): the
    package-wide summary fixpoint is the expensive half of every
    dataflow checker, and with N policies composed it runs ONCE instead
    of N times. Each sub-policy sees only the tags its :meth:`owns`
    claims (plus the shared ``param:<i>`` infrastructure handled by the
    walker itself), so composition cannot change any policy's verdict —
    tag sets here are the union of what each solo run would compute."""

    def __init__(self, policies: list[FlowPolicy]):
        self.policies = list(policies)
        mm: frozenset[str] = frozenset()
        for p in self.policies:
            mm |= p.mutating_methods
        self.mutating_methods = mm

    def _own(self, p: FlowPolicy, tags: Tags) -> Tags:
        return frozenset(t for t in tags if not is_param_tag(t) and p.owns(t))

    def begin_function(self, relpath, cls_name, fn_name):
        for p in self.policies:
            p.begin_function(relpath, cls_name, fn_name)

    def seed(self, expr, cls_name, relpath):
        out = EMPTY
        for p in self.policies:
            out |= p.seed(expr, cls_name, relpath)
        return out

    def element(self, tags, index):
        out = EMPTY
        for p in self.policies:
            out |= p.element(self._own(p, tags), index)
        return out

    def call_result(self, call, recv_tags, arg_tags):
        out = EMPTY
        for p in self.policies:
            out |= p.call_result(
                call, self._own(p, recv_tags),
                [self._own(p, a) for a in arg_tags],
            )
        return out

    def binop(self, node, op, ltags, rtags, report):
        out = EMPTY
        for p in self.policies:
            out |= p.binop(
                node, op, self._own(p, ltags), self._own(p, rtags), report
            )
        return out

    def unary(self, node, op, tags, report):
        out = EMPTY
        for p in self.policies:
            out |= p.unary(node, op, self._own(p, tags), report)
        return out

    def on_mutation(self, node, kind, tags, held, desc):
        for p in self.policies:
            own = self._own(p, tags)
            if own:
                p.on_mutation(node, kind, own, held, desc)

    def on_load(self, expr, cls_name, held, fn_name):
        for p in self.policies:
            p.on_load(expr, cls_name, held, fn_name)

    def on_call(self, call, arg_tags, held, eval_expr):
        for p in self.policies:
            own_eval = (
                lambda e, _p=p: self._own(_p, eval_expr(e))
            )
            p.on_call(
                call, [self._own(p, a) for a in arg_tags], held, own_eval
            )

    def on_compare(self, node, operand_tags):
        for p in self.policies:
            p.on_compare(node, [self._own(p, t) for t in operand_tags])

    def on_bind(self, name, tags, stmt):
        for p in self.policies:
            p.on_bind(name, self._own(p, tags), stmt)

    def on_store(self, kind, name, tags, stmt):
        for p in self.policies:
            p.on_store(kind, name, self._own(p, tags), stmt)

    def on_keyword(self, call, kw_name, tags):
        for p in self.policies:
            p.on_keyword(call, kw_name, self._own(p, tags))

    def finish_call(self, call, tags):
        # each policy rewrites only its own namespace slice; everything
        # else (other namespaces, param pseudo-tags) passes through
        for p in self.policies:
            own = self._own(p, tags)
            tags = (tags - own) | p.finish_call(call, own)
        return tags
