"""Checker ``rcu``: the published ``(state, version)`` snapshot is
immutable, and the raw publish attribute is only touched under the lock.

The batched apply engine's whole reader-side contract (ISSUE 4/7) is
RCU: writers build a NEW state table and publish it with one reference
swap through the snapshot property, so lock-free readers (pull, dump,
the encode cache) see the pre- or post-batch table and never a torn
mix. Nothing enforced that. A single ``state[k] = ...`` on a captured
snapshot — easy to write in a replication or failover path that
"just fixes up one row" — silently breaks every concurrent reader AND
the version stamp (rows from a mutated table no longer match the ``ver``
they were served under), and no test catches it unless the exact
interleaving happens.

This checker makes the discipline static, on dataflow facts
(analysis/dataflow.py) rather than syntax:

- **publish pattern discovery**: a class with a property returning
  ``self.<attr>[0]`` and a setter swapping ``self.<attr> = (...)`` is an
  RCU publisher; the property is the *snapshot property*, ``<attr>``
  the *raw publish attribute* (``ShardServer.state`` / ``_pub``).
- **snapshot immutability**: any value aliasing a published snapshot —
  through assignment, tuple unpacking, subscript reads, helper returns —
  must never be mutated: subscript-store, ``del``, augmented assign,
  or a mutating method (``.update``/``.pop``/...) on it is a finding,
  as is passing it to a callee whose summary mutates that parameter.
- **raw-attribute discipline**: loads of the raw publish attribute
  outside the publisher's own property methods (and ``__init__``) must
  happen under a held lock — everyone else goes through the snapshot
  property, so the one deliberate lock-free tuple capture in the pull
  path is a pragma-documented exception, not an idiom that spreads.
  Stores to the raw attribute outside the setter/``__init__`` are
  flagged unconditionally: every publish must bump the version.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from parameter_server_tpu.analysis.callgraph import CallGraph, shared_callgraph
from parameter_server_tpu.analysis.core import Finding, PackageIndex
from parameter_server_tpu.analysis.dataflow import (
    EMPTY,
    FlowPolicy,
    Tags,
    is_param_tag,
)
from parameter_server_tpu.analysis.flowrun import (
    flow_policy,
    register_flow_policy,
)

#: tag carried by the raw publish tuple; element 0 of it is TAG_SNAP
TAG_PUB = "rcu-pub"
#: tag carried by the published state table (and rows read out of it)
TAG_SNAP = "rcu"


@dataclass(frozen=True)
class Publisher:
    """One discovered RCU-publishing class."""

    cls: str
    relpath: str
    raw_attr: str  # e.g. "_pub"
    snap_prop: str  # property returning <raw_attr>[0], e.g. "state"
    #: every property method (getter/setter names) allowed to touch the
    #: raw attribute without a lock
    prop_methods: frozenset[str]


def _self_attr(expr: ast.AST) -> str | None:
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return expr.attr
    return None


def _returns_self_sub(fndef: ast.FunctionDef) -> tuple[str, int] | None:
    """``return self.<attr>[<int>]`` -> (attr, int)."""
    for stmt in fndef.body:
        if isinstance(stmt, ast.Return) and isinstance(
            stmt.value, ast.Subscript
        ):
            attr = _self_attr(stmt.value.value)
            s = stmt.value.slice
            if attr and isinstance(s, ast.Constant) and isinstance(
                s.value, int
            ):
                return attr, s.value
    return None


def _is_property(fndef: ast.FunctionDef) -> bool:
    return any(
        isinstance(d, ast.Name) and d.id == "property"
        for d in fndef.decorator_list
    )


def _is_setter(fndef: ast.FunctionDef) -> str | None:
    """``@<prop>.setter`` -> prop name."""
    for d in fndef.decorator_list:
        if isinstance(d, ast.Attribute) and d.attr == "setter" and isinstance(
            d.value, ast.Name
        ):
            return d.value.id
    return None


def discover_publishers(index: PackageIndex) -> list[Publisher]:
    out: list[Publisher] = []
    for f in index.files:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            # attr -> {prop reading it}, prop -> element index
            getters: dict[str, list[tuple[str, int]]] = {}
            setter_attrs: dict[str, set[str]] = {}
            prop_methods: set[str] = set()
            for item in node.body:
                if not isinstance(item, ast.FunctionDef):
                    continue
                if _is_property(item):
                    r = _returns_self_sub(item)
                    if r is not None:
                        getters.setdefault(r[0], []).append(
                            (item.name, r[1])
                        )
                        prop_methods.add(item.name)
                prop = _is_setter(item)
                if prop is not None:
                    for sub in ast.walk(item):
                        if isinstance(sub, ast.Assign) and isinstance(
                            sub.value, ast.Tuple
                        ):
                            for t in sub.targets:
                                a = _self_attr(t)
                                if a:
                                    setter_attrs.setdefault(a, set()).add(
                                        prop
                                    )
                                    prop_methods.add(item.name)
            for attr, props in getters.items():
                snap = [p for p, i in props if i == 0]
                if snap and attr in setter_attrs:
                    out.append(Publisher(
                        cls=node.name,
                        relpath=f.relpath,
                        raw_attr=attr,
                        snap_prop=snap[0],
                        prop_methods=frozenset(prop_methods),
                    ))
    return out


class _RcuPolicy(FlowPolicy):
    def __init__(self, pubs: list[Publisher], graph: CallGraph):
        self._graph = graph
        self.pubs = pubs
        self._by_cls = {p.cls: p for p in pubs}
        self._snap_props = {p.snap_prop for p in pubs}
        self._raw_attrs = {p.raw_attr for p in pubs}
        self.findings: list[tuple[int, str, str]] = []  # (line, relpath, msg)
        self._relpath = ""
        self._seen: set[tuple[str, int, str]] = set()

    # -- helpers -----------------------------------------------------------

    def _publisher_for(
        self, expr: ast.Attribute, cls_name: str | None
    ) -> Publisher | None:
        """The Publisher whose snapshot property / raw attr ``expr``
        reads, resolving the receiver like the call graph does: ``self``
        through the MRO, ``self.<attr>`` through attr_types, module
        singletons through global_instances."""
        recv = expr.value
        g = self._graph
        cls: str | None = None
        if isinstance(recv, ast.Name):
            if recv.id == "self" and cls_name is not None:
                for info in g.mro(cls_name):
                    if info.name in self._by_cls:
                        cls = info.name
                        break
            elif recv.id in g.global_instances:
                cls = g.global_instances[recv.id]
        elif (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
            and cls_name is not None
        ):
            for info in g.mro(cls_name):
                t = info.attr_types.get(recv.attr)
                if t is not None:
                    cls = t
                    break
        return self._by_cls.get(cls) if cls else None

    def _add(self, line: int, msg: str) -> None:
        key = (self._relpath, line, msg)
        if key not in self._seen:
            self._seen.add(key)
            self.findings.append((line, self._relpath, msg))

    # -- FlowPolicy hooks --------------------------------------------------

    def owns(self, tag: str) -> bool:
        return tag in (TAG_SNAP, TAG_PUB)

    def begin_function(
        self, relpath: str, cls_name: str | None, fn_name: str
    ) -> None:
        self._relpath = relpath

    def seed(
        self, expr: ast.expr, cls_name: str | None, relpath: str
    ) -> Tags:
        if not isinstance(expr, ast.Attribute):
            return EMPTY
        pub = self._publisher_for(expr, cls_name)
        if pub is None:
            return EMPTY
        if expr.attr == pub.snap_prop:
            return frozenset({TAG_SNAP})
        if expr.attr == pub.raw_attr:
            return frozenset({TAG_PUB})
        return EMPTY

    def element(self, tags: Tags, index: object) -> Tags:
        out = set()
        for t in tags:
            if t == TAG_PUB:
                # element 0 of the publish tuple is the state table;
                # element 1 is the (immutable int) version
                if index == 0 or index is None or index == "iter":
                    out.add(TAG_SNAP)
            elif not is_param_tag(t):
                out.add(t)
        return frozenset(out)

    def on_mutation(
        self, node: ast.AST, kind: str, tags: Tags, held, desc: str
    ) -> None:
        if TAG_SNAP not in tags and TAG_PUB not in tags:
            return
        via = {
            "setitem": "subscript-store into",
            "setattr": "attribute-store into",
            "del": "del on",
            "augassign": "augmented assignment on",
            "call": "mutating method call on",
            "callee": "passing to a callee that mutates",
        }.get(kind, kind)
        self._add(
            getattr(node, "lineno", 0),
            f"{via} {desc}: this value aliases a PUBLISHED RCU snapshot "
            "(immutable after the reference-swap publish) — lock-free "
            "readers and the version stamp both break; build a new "
            "table and publish it through the snapshot property",
        )

    def on_load(
        self, expr: ast.expr, cls_name: str | None, held, fn_name: str
    ) -> None:
        if not isinstance(expr, ast.Attribute):
            return
        if expr.attr not in self._raw_attrs:
            return
        pub = self._publisher_for(expr, cls_name)
        if pub is None or expr.attr != pub.raw_attr:
            return
        if cls_name == pub.cls and (
            fn_name in pub.prop_methods or fn_name == "__init__"
        ):
            return
        if held:
            return  # under a lock: the sanctioned raw access
        self._add(
            expr.lineno,
            f"raw read of RCU publish attribute {pub.cls}.{pub.raw_attr} "
            "outside the apply lock — go through the snapshot property "
            f"({pub.snap_prop}) so readers always capture one published "
            "tuple",
        )


def _check_raw_stores(
    index: PackageIndex, pubs: list[Publisher], out: list[Finding]
) -> None:
    """Stores to the raw publish attribute outside the setter/__init__:
    a publish that bypasses the property setter skips the version bump,
    so a cached ``ver`` would keep validating against changed rows."""
    from parameter_server_tpu.analysis.core import iter_functions

    by_cls = {p.cls: p for p in pubs}
    for f in index.files:
        for cls_name, fndef in iter_functions(f.tree):
            pub = by_cls.get(cls_name or "")
            if pub is None:
                continue
            if fndef.name in pub.prop_methods or fndef.name == "__init__":
                continue
            for sub in ast.walk(fndef):
                targets: list[ast.expr] = []
                if isinstance(sub, ast.Assign):
                    targets = sub.targets
                elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                    targets = [sub.target]
                for t in targets:
                    if _self_attr(t) == pub.raw_attr:
                        out.append(Finding(
                            "rcu", f.relpath, sub.lineno,
                            f"direct store to {pub.cls}.{pub.raw_attr} "
                            "bypasses the snapshot property setter (no "
                            "version bump): publish through "
                            f"self.{pub.snap_prop} = ...",
                        ))


def _policy_factory(index: PackageIndex) -> _RcuPolicy | None:
    pubs = discover_publishers(index)
    if not pubs:
        return None
    return _RcuPolicy(pubs, shared_callgraph(index))


register_flow_policy("rcu", _policy_factory)


def check_rcu(index: PackageIndex) -> list[Finding]:
    policy = flow_policy(index, "rcu")
    if policy is None:  # no RCU publishers in this index
        return []
    assert isinstance(policy, _RcuPolicy)
    out = [
        Finding("rcu", rel, line, msg)
        for line, rel, msg in policy.findings
    ]
    _check_raw_stores(index, policy.pubs, out)
    return out
