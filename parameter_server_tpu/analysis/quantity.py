"""Checkers ``units`` / ``clockdomain`` / ``idtype``: quantity-flow
analysis over the telemetry and wire surface (pslint v3, ISSUE 20).

The repo's own history is the motivation: a sub-ms SSP wait floored to
0 and silenced an SLO rule (PR 12), cross-node wall-clock skew drove
critical-path attribution negative until a hand-written clamp (PR 14),
and the freshness plane (PR 15) threads µs publish-timestamps next to
ms budgets and second-granularity windows through five layers. All
three are *dimensional* bugs — invisible to tests unless the exact
magnitudes collide, trivially visible to a flow analysis that types
every value with its quantity. These checkers ride the PR-8 tag
dataflow (``analysis/dataflow.py``): seeds at sources, propagation
through assignments/helpers/summaries, verdicts at arithmetic,
comparisons and sinks. All three compose into the ONE shared package
fixpoint (``analysis/flowrun.py``) under disjoint tag namespaces.

**units** — dimension lattice ``u:us u:ms u:s u:bytes u:count
u:clocks``, inferred from name suffixes (``_us``/``_ms``/``_s``/
``_bytes``/``_clocks``/``_count``, plus the whole words ``seconds`` and
``nbytes``), from ``time.time()``-family calls (seconds), from literal
factor conversions (``* 1000``: s->ms->us; ``/ 1e3``: us->ms->s;
``1e6`` jumps two rungs), and from the ``[tool.pslint]
unit-conversions`` whitelist (``"fn -> unit"``: a call to ``fn``
returns that unit whatever its body's tags say). Findings: cross-unit
``+``/``-``/comparison, unit-mismatched or unit-unknown durations
flowing into suffixed sinks (names, attributes, header/config keys,
keyword and positional parameters), and duration-valued telemetry
series whose literal name carries no unit suffix (the ``.n``
as-if-microseconds convention counts as a suffix).

**clockdomain** — ``ck:wall`` (``time.time``), ``ck:mono``
(``time.monotonic``), ``ck:perf`` (``time.perf_counter``) and
``ck:foreign`` (a PEER's wall clock echoed through a wire field:
``pts`` and anything in ``[tool.pslint] clock-foreign-keys``), also
seeded by the ``utils.clock`` helper naming convention
(``now_wall_*``/``now_mono_*``/``now_perf_*``). Timestamps are
same-domain-only: subtraction, comparison and min/max across domains
are findings UNLESS the expression sits inside a declared skew clamp —
a function whose name contains ``clamp`` (or is listed in
``[tool.pslint] clock-clamps``), either lexically inside its body or
anywhere inside its call arguments (PR 14's ``_clamp(serve_ts - t0,
op)`` idiom). A same-domain subtraction yields a domain-free duration,
so comparing two durations from different clocks is fine.

**idtype** — opaque identity spaces ``id:cid id:seq id:rank id:ver
id:key id:trace``, seeded from the package vocabulary (``cid``,
``seq``/``rseq``, ``rank``/``worker``, ``ver``/``version``, ``kid``/
``key_id``, ``tid``/``trace_id``) at loads of names, attributes and
header keys. Findings: comparison between different id spaces,
arithmetic on opaque ids (``cid``/``trace``/``key``, and ``ver`` which
is EQUALITY-ONLY — versions roll back on failover, the PR-7 lesson, so
ordering two versions is flagged too; ``seq``/``rank`` stay numeric),
and positional/keyword id swaps at call boundaries where an argument's
id tag contradicts the parameter's id-vocabulary name.
"""

from __future__ import annotations

import ast

from parameter_server_tpu.analysis.callgraph import (
    CallGraph,
    shared_callgraph,
)
from parameter_server_tpu.analysis.core import Finding, PackageIndex
from parameter_server_tpu.analysis.dataflow import (
    EMPTY,
    FlowPolicy,
    Tags,
)
from parameter_server_tpu.analysis.flowrun import (
    flow_policy,
    register_flow_policy,
)

# ---------------------------------------------------------------------------
# vocabularies
# ---------------------------------------------------------------------------

#: identifier suffix token -> unit (the token AFTER the last underscore;
#: single-token names are never suffix-matched except the whole words,
#: so a plain local ``s`` or ``ms`` string var can't pollute the lattice)
_UNIT_TOKENS = {
    "us": "us", "usec": "us",
    "ms": "ms", "msec": "ms",
    "s": "s", "sec": "s", "secs": "s", "seconds": "s",
    "bytes": "bytes",
    "clocks": "clocks",
    "count": "count",
}
_UNIT_WHOLE_WORDS = {"seconds": "s", "nbytes": "bytes"}
_TIME_UNITS = frozenset({"u:us", "u:ms", "u:s"})
_ALL_UNITS = frozenset({"u:us", "u:ms", "u:s", "u:bytes", "u:count",
                        "u:clocks"})

#: literal conversion factors: (unit, factor) -> unit after * / after /
_SCALE_UP = {
    ("u:s", 1000): "u:ms", ("u:ms", 1000): "u:us",
    ("u:s", 1000000): "u:us",
}
_SCALE_DOWN = {
    ("u:us", 1000): "u:ms", ("u:ms", 1000): "u:s",
    ("u:us", 1000000): "u:s",
}

#: numeric identity casts: quantity tags pass straight through
_CAST_FNS = frozenset({"int", "float", "round", "abs", "min", "max", "sum"})

_CLOCK_NAMES = {"ck:wall": "wall (time.time)", "ck:mono": "monotonic",
                "ck:perf": "perf_counter",
                "ck:foreign": "foreign-wall (peer-echoed wire field)"}
_DEFAULT_FOREIGN_KEYS = frozenset({"pts"})

#: id vocabulary: last name token -> id space
_ID_TOKENS = {
    "cid": "cid",
    "seq": "seq", "rseq": "seq",
    "rank": "rank", "worker": "rank",
    "ver": "ver", "version": "ver",
    "kid": "key",
    "tid": "trace",
}
#: two-token tails ``<what>_id``
_ID_PAIRS = {"key": "key", "trace": "trace", "client": "cid",
             "worker": "rank"}
#: id spaces where ANY arithmetic is a finding (ver additionally
#: forbids ordering; seq/rank are genuinely numeric and stay free)
_OPAQUE_IDS = frozenset({"id:cid", "id:ver", "id:key", "id:trace"})


def _tokens(name: str) -> list[str]:
    return [t for t in name.lower().split("_") if t]


def unit_of_name(name: str) -> str | None:
    """``svc_us`` -> "us", ``window_s`` -> "s", ``seconds`` -> "s";
    None when the name declares nothing."""
    low = name.lower()
    if low in _UNIT_WHOLE_WORDS:
        return _UNIT_WHOLE_WORDS[low]
    toks = _tokens(low)
    if len(toks) >= 2:
        return _UNIT_TOKENS.get(toks[-1])
    return None


def id_of_name(name: str) -> str | None:
    """``peer_cid`` -> "cid", ``trace_id`` -> "trace", ``worker`` ->
    "rank"; None when the name is outside the id vocabulary.
    ALL-CAPS names are module constants (bit masks like ``_BF_CID``,
    shift widths like ``NONCE_SHIFT``) — they describe the id's wire
    encoding, they do not HOLD an id value, so they never seed."""
    if name.upper() == name:
        return None
    toks = _tokens(name)
    if not toks:
        return None
    if toks[-1] == "id" and len(toks) >= 2:
        return _ID_PAIRS.get(toks[-2])
    return _ID_TOKENS.get(toks[-1])


def _call_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _const_factor(expr: ast.expr) -> int | None:
    """1000/1e3/1000000/1e6 literal (the conversion rungs) or None."""
    if isinstance(expr, ast.Constant) and isinstance(
        expr.value, (int, float)
    ) and not isinstance(expr.value, bool):
        v = expr.value
        if v in (1000, 1000.0):
            return 1000
        if v in (1000000, 1000000.0):
            return 1000000
    return None


def _time_call_domain(call: ast.Call) -> str | None:
    """``time.time()`` -> ck:wall etc.; also the ``utils.clock`` helper
    naming convention so a snippet (or an unresolved import) still tags."""
    fn = call.func
    name = None
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id == "time":
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    elif isinstance(fn, ast.Attribute):
        name = fn.attr
    if name is None:
        return None
    if name in ("time", "time_ns") or name.startswith("now_wall"):
        return "ck:wall"
    if name in ("monotonic", "monotonic_ns") or name.startswith("now_mono"):
        return "ck:mono"
    if name in ("perf_counter", "perf_counter_ns") or name.startswith(
        "now_perf"
    ):
        return "ck:perf"
    return None


def _time_call_unit(call: ast.Call) -> str | None:
    """The unit a clock call returns: seconds for the ``time`` module
    floats (the ``_ns`` variants are outside the lattice and stay
    untagged on purpose — nothing in this package uses them)."""
    d = _time_call_domain(call)
    if d is None:
        return None
    name = _call_name(call) or ""
    if name.endswith("_ns"):
        return None
    # now_wall_us / now_mono_us carry their unit in the suffix already
    return unit_of_name(name) or "s"


def _callee_params(
    graph: CallGraph, relpath: str, cls_name: str | None, call: ast.Call
) -> list[str] | None:
    """Positional parameter names (self excluded) of the first callee
    the graph resolves for this call; None when unresolved."""
    for owner in graph.callees(relpath, cls_name, call):
        kind, a, b = owner
        if kind == "f":
            fndef = graph.mod_funcs.get((a, b))
        else:
            info = graph.classes.get(a)
            fndef = info.methods.get(b) if info else None
        if fndef is None:
            continue
        args = fndef.args
        names = [p.arg for p in args.posonlyargs + args.args]
        return [n for n in names if n != "self"]
    return None


# ---------------------------------------------------------------------------
# shared policy plumbing
# ---------------------------------------------------------------------------


class _QuantityPolicy(FlowPolicy):
    """Common plumbing: position tracking + deduped finding capture
    (binop runs in both fixpoint passes; report gating plus the dedupe
    set keep each verdict single)."""

    prefix = ""  # tag namespace, e.g. "u:"

    def __init__(self, graph: CallGraph):
        self._graph = graph
        self._relpath = ""
        self._cls: str | None = None
        self._fn = ""
        self.findings: list[tuple[str, int, str]] = []
        self._seen: set[tuple[str, int, str]] = set()

    def owns(self, tag: str) -> bool:
        return tag.startswith(self.prefix)

    def begin_function(
        self, relpath: str, cls_name: str | None, fn_name: str
    ) -> None:
        self._relpath = relpath
        self._cls = cls_name
        self._fn = fn_name

    def _add(self, node: ast.AST, msg: str) -> None:
        key = (self._relpath, getattr(node, "lineno", 0), msg)
        if key not in self._seen:
            self._seen.add(key)
            self.findings.append(key)

    def _mine(self, tags: Tags) -> Tags:
        return frozenset(t for t in tags if t.startswith(self.prefix))


# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------


def _parse_conversions(entries: list[str]) -> dict[str, str]:
    out: dict[str, str] = {}
    for e in entries:
        if "->" not in e:
            continue
        fn, _, unit = e.partition("->")
        fn, unit = fn.strip(), unit.strip()
        if fn and f"u:{unit}" in _ALL_UNITS:
            out[fn] = unit
    return out


class _UnitsPolicy(_QuantityPolicy):
    prefix = "u:"

    def __init__(self, graph: CallGraph, conversions: dict[str, str]):
        super().__init__(graph)
        self._conversions = conversions

    # -- sources -----------------------------------------------------------

    def seed(self, expr, cls_name, relpath):
        if isinstance(expr, ast.Name):
            u = unit_of_name(expr.id)
        elif isinstance(expr, ast.Attribute):
            u = unit_of_name(expr.attr)
        elif isinstance(expr, ast.Subscript) and isinstance(
            expr.slice, ast.Constant
        ) and isinstance(expr.slice.value, str):
            u = unit_of_name(expr.slice.value)
        else:
            u = None
        return frozenset({f"u:{u}"}) if u else EMPTY

    def call_result(self, call, recv_tags, arg_tags):
        name = _call_name(call)
        if name in _CAST_FNS:
            out = EMPTY
            for t in arg_tags:
                out |= self._mine(t)
            return out
        u = _time_call_unit(call)
        if u is None and name is not None:
            u = unit_of_name(name)
        if u is not None:
            return frozenset({f"u:{u}"})
        return super().call_result(call, recv_tags, arg_tags)

    def finish_call(self, call, tags):
        name = _call_name(call)
        conv = self._conversions.get(name or "")
        if conv is not None:
            return frozenset(
                t for t in tags if not t.startswith("u:")
            ) | {f"u:{conv}"}
        return tags

    # -- arithmetic --------------------------------------------------------

    def binop(self, node, op, ltags, rtags, report):
        lu, ru = self._mine(ltags), self._mine(rtags)
        if isinstance(op, ast.Mult):
            # literal rung factor on either side converts a single
            # time-unit operand up the lattice
            if isinstance(node, ast.BinOp):
                l_f = _const_factor(node.left)
                r_f = _const_factor(node.right)
            else:  # AugAssign: x_s *= 1000
                l_f, r_f = None, _const_factor(node.value)
            for f, tags in ((r_f, lu), (l_f, ru)):
                if f is not None and len(tags) == 1:
                    conv = _SCALE_UP.get((next(iter(tags)), f))
                    if conv:
                        return frozenset({conv})
            return lu | ru  # plain scaling keeps the unit
        if isinstance(op, (ast.Div, ast.FloorDiv)):
            value = node.right if isinstance(node, ast.BinOp) else node.value
            f = _const_factor(value)
            if f is not None and len(lu) == 1:
                conv = _SCALE_DOWN.get((next(iter(lu)), f))
                if conv:
                    return frozenset({conv})
            if lu and ru:
                return EMPTY  # ratio (same unit) or rate (cross): unitless
            return lu  # x_us / n stays µs
        if isinstance(op, ast.Mod):
            return lu
        if isinstance(op, (ast.Add, ast.Sub)):
            if lu and ru:
                inter = lu & ru
                if not inter:
                    if report:
                        opname = "+" if isinstance(op, ast.Add) else "-"
                        self._add(node, self._mix_msg(opname, lu, ru))
                    return lu | ru
                return inter
            return lu | ru
        return EMPTY

    def unary(self, node, op, tags, report):
        if isinstance(op, (ast.USub, ast.UAdd)):
            return self._mine(tags)
        return EMPTY

    def _mix_msg(self, what: str, lu: Tags, ru: Tags) -> str:
        return (
            f"cross-unit {what}: operands carry "
            f"{'/'.join(sorted(lu))} vs {'/'.join(sorted(ru))} — "
            "convert explicitly (* 1000, / 1e3, / 1e6) or route through "
            "a declared conversion ([tool.pslint] unit-conversions)"
        )

    def on_compare(self, node, operand_tags):
        tag_sets = [self._mine(t) for t in operand_tags]
        for a, b in zip(tag_sets, tag_sets[1:]):
            if a and b and not (a & b):
                self._add(node, self._mix_msg("comparison", a, b))
                return

    # -- sinks ---------------------------------------------------------------

    def _sink_check(
        self, node: ast.AST, kind: str, name: str, tags: Tags,
        value: ast.expr | None,
    ) -> None:
        want = unit_of_name(name)
        if want is None:
            return
        have = self._mine(tags)
        if have and f"u:{want}" not in have:
            self._add(node, (
                f"value carrying {'/'.join(sorted(have))} flows into "
                f"{kind} '{name}' whose suffix declares u:{want} — "
                "convert at the boundary or fix the name"
            ))
        elif (
            not have
            and f"u:{want}" in _TIME_UNITS
            and isinstance(value, ast.BinOp)
            and isinstance(value.op, ast.Sub)
        ):
            self._add(node, (
                f"duration of unknown unit flows into {kind} '{name}' "
                f"(declared u:{want}): the operands of the subtraction "
                "carry no unit — suffix them, or take the timestamps "
                "from the utils.clock helpers so the lattice can check "
                "this sink"
            ))

    def on_bind(self, name, tags, stmt):
        self._sink_check(stmt, "name", name, tags,
                         getattr(stmt, "value", None))

    def on_store(self, kind, name, tags, stmt):
        label = "attribute" if kind == "attr" else "key"
        self._sink_check(stmt, label, name, tags,
                         getattr(stmt, "value", None))

    def on_keyword(self, call, kw_name, tags):
        value = next(
            (kw.value for kw in call.keywords if kw.arg == kw_name), None
        )
        self._sink_check(call, "keyword argument", kw_name, tags, value)

    def on_call(self, call, arg_tags, held, eval_expr):
        params = _callee_params(self._graph, self._relpath, self._cls, call)
        if params:
            for i, tags in enumerate(arg_tags):
                if i >= len(params):
                    break
                self._sink_check(
                    call, "parameter", params[i], tags,
                    call.args[i] if i < len(call.args) else None,
                )
        # duration-valued telemetry series need a unit-suffixed name
        # (or the .n as-if-µs count convention): series names are how
        # dashboards/SLOs consume these values, so the unit must ride
        # the committed name, not tribal knowledge
        if (
            params
            and params[0] in ("name", "series")
            and call.args
            and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)
            and any(self._mine(t) & _TIME_UNITS for t in arg_tags[1:])
        ):
            series = call.args[0].value
            leaf = series.rsplit(".", 1)[-1]
            if not series.endswith(".n") and unit_of_name(leaf) is None \
                    and unit_of_name(f"x_{leaf}") is None:
                self._add(call, (
                    f"duration-valued series name {series!r} carries no "
                    "unit suffix (and is not a '.n' count) — readers "
                    "can't know the scale; name the unit "
                    f"(e.g. '{series}_s')"
                ))


# ---------------------------------------------------------------------------
# clockdomain
# ---------------------------------------------------------------------------


class _ClockPolicy(_QuantityPolicy):
    prefix = "ck:"

    def __init__(
        self,
        graph: CallGraph,
        foreign_keys: frozenset[str],
        clamp_names: frozenset[str],
        sanctioned: dict[str, set[int]],
    ):
        super().__init__(graph)
        self._foreign = foreign_keys
        self._clamps = clamp_names
        self._sanctioned = sanctioned  # relpath -> linenos inside clamp args
        self._in_clamp = False

    def begin_function(self, relpath, cls_name, fn_name):
        super().begin_function(relpath, cls_name, fn_name)
        self._in_clamp = "clamp" in fn_name.lower() or fn_name in self._clamps

    def _flag(self, node: ast.AST, what: str, a: Tags, b: Tags) -> None:
        if self._in_clamp:
            return
        line = getattr(node, "lineno", 0)
        if line in self._sanctioned.get(self._relpath, ()):
            return

        def names(ts: Tags) -> str:
            return "/".join(_CLOCK_NAMES.get(t, t) for t in sorted(ts))

        self._add(node, (
            f"cross-clock-domain {what}: operands carry {names(a)} vs "
            f"{names(b)} — timestamps are same-domain-only (skew makes "
            "the difference garbage); take both from one clock, or "
            "route the mixing through a declared skew clamp (a function "
            "whose name contains 'clamp', or one listed in "
            "[tool.pslint] clock-clamps)"
        ))

    # -- sources -----------------------------------------------------------

    def seed(self, expr, cls_name, relpath):
        name = None
        if isinstance(expr, ast.Name):
            name = expr.id
        elif isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Subscript) and isinstance(
            expr.slice, ast.Constant
        ) and isinstance(expr.slice.value, str):
            name = expr.slice.value
        if name is not None and (
            name in self._foreign or _tokens(name)[-1:] == ["pts"]
        ):
            return frozenset({"ck:foreign"})
        return EMPTY

    def call_result(self, call, recv_tags, arg_tags):
        d = _time_call_domain(call)
        if d is not None:
            return frozenset({d})
        if _call_name(call) in _CAST_FNS:
            out = EMPTY
            for t in arg_tags:
                out |= self._mine(t)
            return out
        return super().call_result(call, recv_tags, arg_tags)

    # -- same-domain-only operations -----------------------------------------

    def binop(self, node, op, ltags, rtags, report):
        lc, rc = self._mine(ltags), self._mine(rtags)
        if isinstance(op, ast.Sub):
            if lc and rc:
                if not (lc & rc) and report:
                    self._flag(node, "subtraction", lc, rc)
                return EMPTY  # ts - ts = duration: domain-free
            return EMPTY  # unknown mix: stay quiet, stay untagged
        if isinstance(op, ast.Add):
            return lc | rc  # ts + duration keeps the domain
        if isinstance(op, (ast.Mult, ast.Div)):
            return lc | rc  # unit rescaling keeps the domain
        return EMPTY

    def unary(self, node, op, tags, report):
        if isinstance(op, (ast.USub, ast.UAdd)):
            return self._mine(tags)
        return EMPTY

    def on_compare(self, node, operand_tags):
        tag_sets = [self._mine(t) for t in operand_tags]
        for a, b in zip(tag_sets, tag_sets[1:]):
            if a and b and not (a & b):
                self._flag(node, "comparison", a, b)
                return

    def on_call(self, call, arg_tags, held, eval_expr):
        name = _call_name(call)
        if name not in ("min", "max") or len(arg_tags) < 2:
            return
        domains = [self._mine(t) for t in arg_tags if self._mine(t)]
        for a, b in zip(domains, domains[1:]):
            if not (a & b):
                self._flag(call, f"{name}()", a, b)
                return


def _collect_clamp_sanctioned(
    index: PackageIndex, clamp_names: frozenset[str]
) -> dict[str, set[int]]:
    """relpath -> line numbers lexically inside the ARGUMENTS of a call
    to a declared skew clamp: ``_clamp(serve_ts - issue_ts, op)`` mixes
    domains inside the clamp call itself, and that is the sanctioned
    place to do it."""
    out: dict[str, set[int]] = {}
    for f in index.files:
        lines: set[int] = set()
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name is None or (
                "clamp" not in name.lower() and name not in clamp_names
            ):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if hasattr(sub, "lineno"):
                        lines.add(sub.lineno)
        if lines:
            out[f.relpath] = lines
    return out


# ---------------------------------------------------------------------------
# idtype
# ---------------------------------------------------------------------------


class _IdPolicy(_QuantityPolicy):
    prefix = "id:"

    def seed(self, expr, cls_name, relpath):
        if isinstance(expr, ast.Name):
            t = id_of_name(expr.id)
        elif isinstance(expr, ast.Attribute):
            t = id_of_name(expr.attr)
        elif isinstance(expr, ast.Subscript) and isinstance(
            expr.slice, ast.Constant
        ) and isinstance(expr.slice.value, str):
            t = id_of_name(expr.slice.value)
        else:
            t = None
        return frozenset({f"id:{t}"}) if t else EMPTY

    def call_result(self, call, recv_tags, arg_tags):
        if _call_name(call) in _CAST_FNS:
            out = EMPTY
            for t in arg_tags:
                out |= self._mine(t)
            return out
        return super().call_result(call, recv_tags, arg_tags)

    # -- capabilities --------------------------------------------------------

    def binop(self, node, op, ltags, rtags, report):
        li, ri = self._mine(ltags), self._mine(rtags)
        if isinstance(
            op, (ast.BitOr, ast.BitAnd, ast.BitXor, ast.LShift, ast.RShift)
        ):
            # bit packing/masking IS the encode/decode of an opaque id
            # (header flag words, the ver<<shift|nonce life stamp) —
            # structure, not arithmetic; the value keeps its space
            return li | ri
        opaque = (li | ri) & _OPAQUE_IDS
        if opaque and report:
            which = "/".join(sorted(opaque))
            extra = (
                " (id:ver is EQUALITY-ONLY: versions roll back on "
                "failover, so even +1 outside the publisher's setter "
                "forges a stamp)"
                if "id:ver" in opaque else ""
            )
            self._add(node, (
                f"arithmetic on opaque id {which}: identity tokens are "
                f"not numbers{extra} — derive a new id at its "
                "construction site instead"
            ))
        return li | ri  # id arith (where legal: seq/rank) keeps the space

    def unary(self, node, op, tags, report):
        if isinstance(op, (ast.USub, ast.UAdd)):
            return self._mine(tags)
        return EMPTY

    def on_compare(self, node, operand_tags):
        tag_sets = [self._mine(t) for t in operand_tags]
        ordered = any(
            isinstance(o, (ast.Lt, ast.LtE, ast.Gt, ast.GtE))
            for o in node.ops
        )
        for a, b in zip(tag_sets, tag_sets[1:]):
            if a and b and not (a & b):
                self._add(node, (
                    f"cross-identity comparison: {'/'.join(sorted(a))} "
                    f"vs {'/'.join(sorted(b))} — these id spaces never "
                    "intersect, so this is a type confusion the runtime "
                    "can't see (swapped variables?)"
                ))
                return
            if ordered and "id:ver" in a and "id:ver" in b:
                self._add(node, (
                    "ordering comparison between version stamps: id:ver "
                    "is equality-only (a failover can roll the published "
                    "version BACK, so 'newer' is undecidable) — "
                    "revalidate with ==/!="
                ))
                return

    # -- call-boundary swaps ---------------------------------------------------

    def _param_check(
        self, node: ast.AST, where: str, pname: str, tags: Tags
    ) -> None:
        want = id_of_name(pname)
        have = self._mine(tags)
        if want is None or not have:
            return
        if f"id:{want}" not in have:
            self._add(node, (
                f"{where} carries {'/'.join(sorted(have))} but the "
                f"parameter is named '{pname}' (id:{want}) — id spaces "
                "swapped at the call boundary"
            ))

    def on_call(self, call, arg_tags, held, eval_expr):
        params = _callee_params(self._graph, self._relpath, self._cls, call)
        if not params:
            return
        for i, tags in enumerate(arg_tags):
            if i >= len(params):
                break
            self._param_check(call, f"argument {i}", params[i], tags)

    def on_keyword(self, call, kw_name, tags):
        self._param_check(call, "keyword argument", kw_name, tags)


# ---------------------------------------------------------------------------
# factories + checkers
# ---------------------------------------------------------------------------


def _units_factory(index: PackageIndex) -> _UnitsPolicy:
    return _UnitsPolicy(
        shared_callgraph(index),
        _parse_conversions(index.config.unit_conversions),
    )


def _clock_factory(index: PackageIndex) -> _ClockPolicy:
    clamps = frozenset(index.config.clock_clamps)
    return _ClockPolicy(
        shared_callgraph(index),
        _DEFAULT_FOREIGN_KEYS | frozenset(index.config.clock_foreign_keys),
        clamps,
        _collect_clamp_sanctioned(index, clamps),
    )


def _id_factory(index: PackageIndex) -> _IdPolicy:
    return _IdPolicy(shared_callgraph(index))


register_flow_policy("units", _units_factory)
register_flow_policy("clockdomain", _clock_factory)
register_flow_policy("idtype", _id_factory)


def _findings_of(index: PackageIndex, name: str) -> list[Finding]:
    policy = flow_policy(index, name)
    assert isinstance(policy, _QuantityPolicy)
    return [
        Finding(name, rel, line, msg)
        for rel, line, msg in policy.findings
    ]


def check_units(index: PackageIndex) -> list[Finding]:
    return _findings_of(index, "units")


def check_clockdomain(index: PackageIndex) -> list[Finding]:
    return _findings_of(index, "clockdomain")


def check_idtype(index: PackageIndex) -> list[Finding]:
    return _findings_of(index, "idtype")
