"""Checker ``replycache-contract``: the reply-cache exemption sets stay
consistent with the commands each server actually serves.

``RpcServer``'s exactly-once machinery is driven by per-construction-site
command inventories: ``idempotent_cmds`` (resends BYPASS the reply cache —
re-apply beats pinning a model-sized reply), ``blocking_cmds`` (coalesced
replies must flush before dispatch parks the thread) and ``prio_cmds``
(reply lane routing). Those sets are string literals, and the commands a
handler serves are another inventory entirely (``_cmd_<name>`` methods on
the coordinator, ``cmd == "<name>"`` dispatch in the shard server) — so a
renamed or removed command silently leaves a STALE exemption behind, and
the failure is behavioral, not syntactic: a command that used to bypass
the reply cache starts getting its (possibly multi-MiB) replies pinned,
or a blocking command stops flushing withheld replies before parking.

This checker derives both inventories from the AST and flags the drift,
in both directions:

- every command named in an ``idempotent_cmds`` / ``blocking_cmds`` /
  ``prio_cmds`` literal at an ``RpcServer(...)`` construction site must
  be a command the constructing class's handler actually serves;
- every served command must carry a compact id in the wire's append-only
  ``_CMD_IDS`` table (else the binary header codec silently degrades
  that command to string-cmd framing forever — a new command must be
  registered, ids are wire contract).

Like the counter/config contracts, the inventories are DERIVED — there
is no hand-maintained list for this checker to drift from.
"""

from __future__ import annotations

import ast

from parameter_server_tpu.analysis.core import Finding, PackageIndex

Sites = list[tuple[str, int]]

#: RpcServer keywords holding command-name inventories
_SET_KEYWORDS = ("idempotent_cmds", "blocking_cmds", "prio_cmds")


def _strings_in(node: ast.AST) -> set[str]:
    return {
        sub.value
        for sub in ast.walk(node)
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str)
    }


def _is_cmd_expr(expr: ast.AST) -> bool:
    """Does ``expr`` read the dispatched command? Matches the package's
    dispatch idioms: a ``cmd`` local, ``h["cmd"]`` / ``header["cmd"]``
    subscripts, and ``.cmd`` attributes."""
    if isinstance(expr, ast.Name):
        return expr.id == "cmd" or expr.id.endswith("_cmd")
    if isinstance(expr, ast.Subscript):
        s = expr.slice
        return isinstance(s, ast.Constant) and s.value == "cmd"
    if isinstance(expr, ast.Attribute):
        return expr.attr == "cmd"
    return False


def served_cmds(cls: ast.ClassDef) -> set[str]:
    """Commands a handler class serves: ``_cmd_<name>`` methods plus
    string literals equality-compared against the dispatched command."""
    out: set[str] = set()
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name.startswith("_cmd_"):
                out.add(node.name[len("_cmd_"):])
    for sub in ast.walk(cls):
        if not isinstance(sub, ast.Compare) or len(sub.ops) != 1:
            continue
        if not isinstance(sub.ops[0], ast.Eq):
            continue
        left, right = sub.left, sub.comparators[0]
        lit = None
        if isinstance(right, ast.Constant) and isinstance(right.value, str):
            if _is_cmd_expr(left):
                lit = right.value
        elif isinstance(left, ast.Constant) and isinstance(left.value, str):
            if _is_cmd_expr(right):
                lit = left.value
        if lit is not None:
            out.add(lit)
    return out


def declared_sets(
    cls: ast.ClassDef,
) -> list[tuple[str, set[str], int]]:
    """``(keyword, names, line)`` for every command inventory passed to
    an ``RpcServer(...)`` construction inside ``cls``."""
    out: list[tuple[str, set[str], int]] = []
    for sub in ast.walk(cls):
        if not isinstance(sub, ast.Call):
            continue
        fn = sub.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else ""
        )
        if not name.endswith("RpcServer"):
            continue
        for kw in sub.keywords:
            if kw.arg in _SET_KEYWORDS:
                out.append((kw.arg, _strings_in(kw.value), sub.lineno))
    return out


def cmd_id_inventory(index: PackageIndex) -> set[str] | None:
    """Every command name registered in a ``_CMD_IDS`` assignment in the
    analyzed tree (None when the tree defines no such table — snippet
    indexes without a wire module skip the id check)."""
    found = None
    for f in index.files:
        for node in ast.walk(f.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            if node.value is None:
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "_CMD_IDS":
                    found = (found or set()) | _strings_in(node.value)
    return found


def check_replycache_contract(index: PackageIndex) -> list[Finding]:
    cmd_ids = cmd_id_inventory(index)
    out: list[Finding] = []
    for f in index.files:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            decls = declared_sets(node)
            if not decls:
                continue  # not a server-owning class
            served = served_cmds(node)
            if not served:
                continue  # handler lives elsewhere (generic RpcServer use)
            for kw, names, line in decls:
                for name in sorted(names - served):
                    out.append(Finding(
                        "replycache-contract", f.relpath, line,
                        f"{kw} names {name!r} but {node.name}'s handler "
                        "serves no such command — a stale entry here "
                        "silently changes reply-cache/flush behavior "
                        "for a command that no longer exists",
                    ))
            if cmd_ids is not None:
                for name in sorted(served - cmd_ids):
                    out.append(Finding(
                        "replycache-contract", f.relpath, node.lineno,
                        f"{node.name} serves {name!r} but _CMD_IDS has "
                        "no compact id for it — the binary header codec "
                        "degrades this command to string-cmd framing; "
                        "register it (ids are append-only wire contract)",
                    ))
    return out
