"""Seeded interleaving explorer (the scheduling complement of the
lock-order witness).

The witness catches *ordering* bugs; it cannot make an unlikely
interleaving HAPPEN. This module can, two ways, both driven by one seed
so a failure is a replayable artifact instead of a flake:

**Strict mode** (:class:`StrictSched`) is a PCT-style cooperative
scheduler for crafted concurrency scenarios: every managed thread owns
the single run token between *scheduling points* (lock acquire/release,
explicit ``point()`` boundaries), priorities are drawn from the seeded
RNG and reshuffled at seeded change points, and the highest-priority
runnable thread is always the one scheduled — so execution is a
deterministic serialization chosen adversarially by the seed. Same seed
=> same schedule trace => same failure, every run; a failing seed
printed once reproduces forever.

**Perturb mode** (``install(seed)`` / ``PS_SCHED=<seed>``) arms the
whole package the way the witness does: ``threading.Lock``/``RLock``/
``Condition`` and ``queue.Queue`` CONSTRUCTION in package modules is
wrapped so every acquire/release/put/get is a boundary, and the
``ShardServer`` RCU publish (the snapshot property setter) gets its own
boundary. At each boundary a per-site RNG stream derived from the seed
decides whether to yield the OS slice or inject a sub-millisecond stall
— forcing the adversarial interleavings (reader between publish and
ack, push racing a pull's cache fill) that free-running CI almost never
takes. Per-site decision streams depend only on (seed, site), so a
given boundary makes the same decision sequence in every run with that
seed. Armed tests print the seed on failure; re-arming with it replays
the same per-site schedule pressure.

Scope mirrors the witness: only package-constructed primitives are
instrumented, analysis/ itself is exempt, and stdlib internals keep raw
locks.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time

ENV_VAR = "PS_SCHED"

_PKG_MARKER = os.sep + "parameter_server_tpu" + os.sep

#: perturb-mode tuning: P(yield) + P(stall) per boundary, stall bound.
#: Small enough to keep an armed tier-1 test inside its budget, large
#: enough that a few thousand boundaries take many adversarial breaks.
_P_YIELD = 0.15
_P_STALL = 0.05
_STALL_MAX_S = 0.002


class SchedulerStall(RuntimeError):
    """Strict mode wedged: every managed thread is blocked (a real
    deadlock the schedule drove into, or an uninstrumented wait)."""


# ---------------------------------------------------------------------------
# perturb mode: package-wide seeded boundary perturbation
# ---------------------------------------------------------------------------


class _Perturb:
    """Per-site seeded decision streams + the armed-run decision log."""

    def __init__(self, seed: int):
        self.seed = seed
        self._lock = threading.Lock()  # guards the rng/decision maps
        self._rngs: dict[str, random.Random] = {}
        #: site -> list of decision codes (0 run on, 1 yield, 2 stall) —
        #: the replayable "schedule" an armed run took at each boundary
        self.decisions: dict[str, list[int]] = {}

    def point(self, site: str) -> None:
        with self._lock:
            rng = self._rngs.get(site)
            if rng is None:
                # stream identity is (seed, site): a site's decision
                # sequence is the same in every run with this seed,
                # independent of which threads hit it in what order
                rng = self._rngs[site] = random.Random(f"{self.seed}:{site}")
            r = rng.random()
            stall = rng.random() * _STALL_MAX_S  # drawn either way: the
            # stream must advance identically whatever r said
            code = 2 if r < _P_STALL else (1 if r < _P_STALL + _P_YIELD else 0)
            log = self.decisions.setdefault(site, [])
            if len(log) < 10000:  # bound the log, not the decisions
                log.append(code)
        if code == 2:
            time.sleep(stall)
        elif code == 1:
            time.sleep(0)  # release the GIL/OS slice


_perturb: _Perturb | None = None
_orig: dict[str, object] = {}
_installs = 0


def _caller_site(depth: int = 2) -> str | None:
    f = sys._getframe(depth)
    fn = f.f_code.co_filename
    i = fn.rfind(_PKG_MARKER)
    if i < 0:
        return None
    rel = fn[i + len(_PKG_MARKER):].replace(os.sep, "/")
    if rel.startswith("analysis/"):
        return None  # the explorer must not instrument itself
    return f"{rel}:{f.f_lineno}"


class BoundaryLock:
    """Boundary-injecting proxy around whatever lock the current
    ``threading.Lock`` factory produces (the raw lock, or the witness's
    ``WitnessLock`` when both tools are armed — the explorer composes on
    top, so forced interleavings still get order-checked)."""

    def __init__(self, inner, site: str):
        self._psx_inner = inner
        self._psx_site = site

    def acquire(self, blocking: bool = True, timeout: float = -1):
        p = _perturb
        if p is not None:
            p.point("lock:" + self._psx_site)
        return self._psx_inner.acquire(blocking, timeout)

    def release(self) -> None:
        self._psx_inner.release()
        p = _perturb
        if p is not None:
            p.point("unlock:" + self._psx_site)

    def __enter__(self) -> "BoundaryLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self):
        return self._psx_inner.locked()

    def __getattr__(self, name: str):
        return getattr(self._psx_inner, name)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<BoundaryLock {self._psx_site} of {self._psx_inner!r}>"


def _lock_factory():
    site = _caller_site()
    inner = _orig["Lock"]()
    return BoundaryLock(inner, site) if site else inner


def _rlock_factory():
    site = _caller_site()
    inner = _orig["RLock"]()
    return BoundaryLock(inner, site) if site else inner


def _cond_factory(lock=None):
    if lock is None:
        site = _caller_site()
        if site is not None:
            lock = BoundaryLock(_orig["RLock"](), site)
    if lock is not None:
        return _orig["Condition"](lock)
    return _orig["Condition"]()


def _queue_factory(maxsize: int = 0):
    """Package-constructed queues get put/get boundaries (the apply
    queue is where push batches form — exactly the interleaving the
    batched-apply chaos tests need pressure on)."""
    site = _caller_site()
    q = _orig["Queue"](maxsize)
    if site is None:
        return q
    orig_put, orig_get = q.put, q.get

    def put(item, block=True, timeout=None):
        p = _perturb
        if p is not None:
            p.point(f"queue.put:{site}")
        return orig_put(item, block, timeout)

    def get(block=True, timeout=None):
        p = _perturb
        if p is not None:
            p.point(f"queue.get:{site}")
        return orig_get(block, timeout)

    q.put, q.get = put, get
    return q


def _wrap_rcu_publish() -> None:
    """Give the ShardServer RCU publish its own boundary: a perturbed
    pause between building a state and swapping the reference is the
    window every snapshot/version coherence bug lives in."""
    ms = sys.modules.get("parameter_server_tpu.parallel.multislice")
    if ms is None:
        try:  # arm-time import is fine: PS_SCHED runs are explicit
            import parameter_server_tpu.parallel.multislice as ms  # type: ignore
        except Exception:  # pragma: no cover - torn env
            return
    cls = getattr(ms, "ShardServer", None)
    prop = getattr(cls, "state", None) if cls is not None else None
    if cls is None or not isinstance(prop, property) or prop.fset is None:
        return  # pragma: no cover - refactored away; boundary just absent
    _orig["ShardServer.state"] = (cls, prop)
    orig_set = prop.fset

    def setter(self, new_state):
        p = _perturb
        if p is not None:
            p.point("rcu-publish:ShardServer.state")
        orig_set(self, new_state)
        if p is not None:
            p.point("rcu-published:ShardServer.state")

    setattr(cls, "state", property(prop.fget, setter))


def install(seed: int = 0) -> None:
    """Arm perturb mode process-wide (idempotent, reference-counted,
    composes over an armed witness — the explorer wraps whatever lock
    factory is current)."""
    global _perturb, _installs
    _installs += 1
    if _installs > 1:
        return
    import queue as queue_mod

    _perturb = _Perturb(int(seed))
    _orig["Lock"] = threading.Lock
    _orig["RLock"] = threading.RLock
    _orig["Condition"] = threading.Condition
    _orig["Queue"] = queue_mod.Queue
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _cond_factory
    queue_mod.Queue = _queue_factory
    _wrap_rcu_publish()


def uninstall() -> None:
    global _perturb, _installs
    if _installs == 0:
        return
    _installs -= 1
    if _installs > 0:
        return
    import queue as queue_mod

    threading.Lock = _orig["Lock"]
    threading.RLock = _orig["RLock"]
    threading.Condition = _orig["Condition"]
    queue_mod.Queue = _orig["Queue"]
    wrapped = _orig.pop("ShardServer.state", None)
    if wrapped is not None:
        cls, prop = wrapped
        setattr(cls, "state", prop)
    _perturb = None


def installed() -> bool:
    return _installs > 0


def current_seed() -> int | None:
    return _perturb.seed if _perturb is not None else None


def decisions() -> dict[str, list[int]]:
    """The armed run's per-site decision log (replayable: same seed =>
    same per-site sequences)."""
    return (
        {k: list(v) for k, v in _perturb.decisions.items()}
        if _perturb is not None
        else {}
    )


def replay_hint() -> str:
    return f"replay this interleaving with {ENV_VAR}={current_seed()}"


def maybe_install_from_env() -> bool:
    """Chaos-style opt-in: ``PS_SCHED=<seed>`` arms perturb mode."""
    v = os.environ.get(ENV_VAR, "")
    if v not in ("", "0"):
        try:
            seed = int(v)
        except ValueError:
            seed = 1
        install(seed)
        return True
    return False


# ---------------------------------------------------------------------------
# seed corpus + budgeted search (``cli explore``)
# ---------------------------------------------------------------------------

#: committed corpus of schedule seeds that once FAILED a test: the
#: explorer-armed tier-1 run replays them forever (a fixed bug's
#: breaking interleaving becomes its regression test), and ``cli
#: explore`` appends new ones
CORPUS_SCHEMA = "pssched/1"


def load_corpus(path: str) -> dict[str, list[int]]:
    """test node id -> failing seeds. Missing/foreign files read as
    empty — exploration must bootstrap from nothing."""
    import json

    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    if doc.get("schema") != CORPUS_SCHEMA:
        return {}
    return {
        str(t): sorted({int(s) for s in seeds})
        for t, seeds in (doc.get("tests") or {}).items()
    }


def corpus_seeds(path: str, test: str) -> list[int]:
    """The committed failing seeds for one test node id (the set the
    explorer-armed tier-1 run replays on top of its fixed seed)."""
    return load_corpus(path).get(test, [])


def record_failing_seeds(
    path: str, test: str, seeds: list[int]
) -> dict[str, list[int]]:
    """Merge newly found failing seeds into the corpus file (created if
    missing; seeds dedup'd and sorted so the diff is reviewable). A file
    that EXISTS but doesn't parse as this schema (torn merge, future
    build) refuses the write — load_corpus reads such files as empty,
    and silently rewriting would destroy every committed seed."""
    import json

    corpus = load_corpus(path)
    if not corpus and os.path.exists(path) and os.path.getsize(path):
        try:
            with open(path) as f:
                ours = json.load(f).get("schema") == CORPUS_SCHEMA
        except (OSError, ValueError):
            ours = False
        if not ours:
            raise RuntimeError(
                f"corpus {path} exists but is not a {CORPUS_SCHEMA} "
                "file (torn write? newer schema?) — refusing to "
                "overwrite it; fix or remove the file first"
            )
    corpus[test] = sorted(set(corpus.get(test, [])) | set(seeds))
    # atomic tmp+rename (the flightrec dump idiom): a write interrupted
    # mid-dump must never leave a torn corpus the tier-1 replay would
    # read as empty
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({
            "schema": CORPUS_SCHEMA,
            "comment": (
                "schedule seeds that once failed a test under the "
                "PS_SCHED interleaving explorer; cli explore appends, "
                "the explorer-armed tier-1 run replays. Replay one by "
                "hand: PS_SCHED=<seed> python -m pytest <test>"
            ),
            "tests": {t: corpus[t] for t in sorted(corpus)},
        }, f, indent=1, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)
    return corpus


class SearchError(RuntimeError):
    """The search INFRASTRUCTURE broke mid-budget (pytest could not
    run — collection/usage/internal error), as opposed to a seed
    failing the test. Carries the failing seeds found before the break
    so the caller can still record/report a long search's finds."""

    def __init__(self, seed: int, failing: list[int], cause: Exception):
        super().__init__(f"seed {seed}: {cause}")
        self.seed = seed
        self.failing = list(failing)


def search_seeds(
    test: str,
    budget: int,
    start_seed: int = 1,
    runner=None,
    on_result=None,
    timeout_s: float = 120.0,
) -> list[int]:
    """Budgeted schedule-seed search: run ``test`` under
    ``PS_SCHED=<seed>`` for seeds ``start_seed .. start_seed+budget-1``
    and return the seeds that FAILED it (the interleavings worth
    keeping). ``runner(seed) -> bool`` (True = test passed) defaults to
    a pytest subprocess per seed — a fresh interpreter per seed is what
    makes the arming honest (the explorer wraps construction, so it
    must be armed before the package imports). A seed that WEDGES the
    test past ``timeout_s`` counts as failing: a deadlock interleaving
    is the search's most valuable find, not a reason to hang it. A
    runner that RAISES aborts the search with :class:`SearchError`
    carrying the finds so far — an hours-long budget must not lose its
    results to one transient infra hiccup."""
    if runner is None:
        runner = _pytest_runner(test, timeout_s=timeout_s)
    failing: list[int] = []
    for seed in range(start_seed, start_seed + budget):
        try:
            passed = bool(runner(seed))
        except Exception as e:
            raise SearchError(seed, failing, e) from e
        if not passed:
            failing.append(seed)
        if on_result is not None:
            on_result(seed, passed)
    return failing


def _pytest_runner(test: str, timeout_s: float = 120.0):
    import signal
    import subprocess
    import sys as _sys

    # a relative node id ("tests/test_x.py::T::t") only collects from
    # the repo root — anchor the subprocess there when the file part
    # isn't visible from the caller's cwd, so `cli explore` works from
    # any directory instead of recording collection errors as "finds"
    cwd = None
    file_part = test.split("::", 1)[0]
    if not os.path.exists(file_part):
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        if os.path.exists(os.path.join(repo_root, file_part)):
            cwd = repo_root

    def run(seed: int) -> bool:
        env = dict(os.environ, **{ENV_VAR: str(seed)})
        # own session so a timed-out child's whole process GROUP dies —
        # the test may have launch_local'd server processes a bare
        # kill() of pytest would orphan
        proc = subprocess.Popen(
            [_sys.executable, "-m", "pytest", test, "-x", "-q",
             "--no-header", "-p", "no:cacheprovider"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, start_new_session=True, cwd=cwd,
        )
        try:
            out, err = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            proc.communicate()
            return False  # a wedged interleaving IS a failing seed
        # pytest: 0 = passed, 1 = tests ran and failed. Anything else
        # (collection/usage/internal error, no tests collected) means
        # the SEARCH is broken, not the interleaving — recording such a
        # seed would poison the corpus tier-1 replays
        if proc.returncode not in (0, 1):
            tail = "\n".join((out + err).strip().splitlines()[-5:])
            raise RuntimeError(
                f"explore: pytest could not run {test!r} "
                f"(exit {proc.returncode}):\n{tail}"
            )
        return proc.returncode == 0

    return run


# ---------------------------------------------------------------------------
# strict mode: deterministic PCT scheduling of crafted scenarios
# ---------------------------------------------------------------------------


class _MThread:
    __slots__ = ("name", "order", "prio", "event", "state", "thread",
                 "blocked_on")

    def __init__(self, name: str, order: int, prio: float):
        self.name = name
        self.order = order  # registration order: the deterministic tiebreak
        self.prio = prio
        self.event = threading.Event()
        self.state = "new"  # new | ready | running | blocked | done
        self.thread: threading.Thread | None = None
        self.blocked_on: object = None


class StrictLock:
    """A lock whose contention is scheduled, not raced: managed threads
    try-acquire and, on failure, hand the token back to the scheduler
    instead of parking in the OS — so who wins a contended lock is the
    seed's choice, deterministically."""

    def __init__(self, sched: "StrictSched", name: str):
        self._sched = sched
        self._name = name
        self._inner = threading.Lock()

    def acquire(self) -> bool:
        self._sched.point(f"acquire:{self._name}")
        while not self._inner.acquire(False):
            self._sched._block_on(self)
        return True

    def release(self) -> None:
        self._inner.release()
        self._sched._unblock(self)
        self._sched.point(f"release:{self._name}")

    def __enter__(self) -> "StrictLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class StrictSched:
    """Deterministic PCT scheduler. Usage::

        sched = StrictSched(seed)
        lock = sched.lock("l")
        sched.spawn(worker_a, "a")
        sched.spawn(worker_b, "b")
        sched.run()            # raises nothing; failures collected
        sched.trace            # [(thread, site)...] — THE schedule
        sched.failures         # [(thread, exc)...], seed printed on any

    Managed threads run one at a time; the token moves only at
    scheduling points (``point()``, StrictLock operations, spawn/exit).
    Priorities come from the seeded RNG and are reassigned at seeded
    change points — the PCT idea: a random prioritization explores
    ordering bugs of depth d with known probability, and the SEED is the
    whole schedule."""

    #: a token wait longer than this means the holder parked in an
    #: uninstrumented wait — steal the token rather than hang the suite
    _STEAL_S = 2.0

    def __init__(self, seed: int, change_p: float = 0.3):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        #: per-point probability the running thread's priority is
        #: redrawn (the PCT change points — this is what creates
        #: mid-critical-window preemptions; 0 degenerates to a single
        #: random serialization)
        self._change_p = float(change_p)
        self._lock = threading.Lock()  # guards scheduler state
        self._threads: dict[str, _MThread] = {}
        self._started = False
        self._step = 0
        self.trace: list[tuple[str, str]] = []
        self.failures: list[tuple[str, BaseException]] = []
        self._tls = threading.local()

    # -- construction ------------------------------------------------------

    def lock(self, name: str) -> StrictLock:
        return StrictLock(self, name)

    def spawn(self, target, name: str) -> None:
        """Register + start one managed thread (it parks at its entry
        point until the scheduler picks it). Call in a deterministic
        order — registration order is the priority tiebreak."""
        with self._lock:
            if self._started:
                raise RuntimeError("spawn() after run()")
            m = _MThread(name, len(self._threads), self._rng.random())
            self._threads[name] = m

        def body() -> None:
            self._tls_name(name)
            self._wait_for_token(m)
            try:
                target()
            except BaseException as e:  # noqa: BLE001 — recorded + replayable
                with self._lock:
                    self.failures.append((name, e))
                print(
                    f"[explorer] managed thread {name!r} failed under "
                    f"seed {self.seed}: {e!r} — {replay_strict_hint(self.seed)}",
                    file=sys.stderr,
                )
            finally:
                self._exit(m)

        m.thread = threading.Thread(target=body, name=name, daemon=True)
        m.state = "ready"
        m.thread.start()

    def _tls_name(self, name: str) -> None:
        self._tls.name = name

    def _me(self) -> _MThread | None:
        return self._threads.get(getattr(self._tls, "name", ""))

    # -- the token ---------------------------------------------------------

    def run(self, timeout: float = 30.0) -> None:
        """Schedule until every managed thread exits."""
        with self._lock:
            self._started = True
            self._dispatch_locked()
        deadline = time.monotonic() + timeout
        for m in self._threads.values():
            t = m.thread
            if t is not None:
                t.join(max(0.0, deadline - time.monotonic()))
        alive = [m.name for m in self._threads.values()
                 if m.thread is not None and m.thread.is_alive()]
        if alive:
            raise SchedulerStall(
                f"managed threads still alive after {timeout}s under "
                f"seed {self.seed}: {alive}"
            )

    def point(self, site: str) -> None:
        """One scheduling point: log, maybe reshuffle this thread's
        priority, hand the token to the highest-priority ready thread
        (possibly this one)."""
        m = self._me()
        if m is None:
            return  # unmanaged thread (the test's main thread): no-op
        with self._lock:
            self._step += 1
            self.trace.append((m.name, site))
            if self._rng.random() < self._change_p:
                m.prio = self._rng.random()
            m.state = "ready"
            self._dispatch_locked()
        self._wait_for_token(m)

    def _block_on(self, lock: StrictLock) -> None:
        m = self._me()
        if m is None:  # unmanaged: really park (strict locks are raw)
            lock._inner.acquire()
            lock._inner.release()
            return
        with self._lock:
            self.trace.append((m.name, f"blocked:{lock._name}"))
            m.state = "blocked"
            m.blocked_on = lock
            self._dispatch_locked()
        self._wait_for_token(m)

    def _unblock(self, lock: StrictLock) -> None:
        with self._lock:
            for m in self._threads.values():
                if m.state == "blocked" and m.blocked_on is lock:
                    m.state = "ready"
                    m.blocked_on = None

    def _exit(self, m: _MThread) -> None:
        with self._lock:
            self.trace.append((m.name, "exit"))
            m.state = "done"
            self._dispatch_locked()

    def _dispatch_locked(self) -> None:
        """Pick the highest-priority ready thread and wake it (caller
        holds ``self._lock``)."""
        ready = [
            t for t in self._threads.values() if t.state == "ready"
        ]
        if not ready:
            return
        nxt = max(ready, key=lambda t: (t.prio, -t.order))
        nxt.state = "running"
        nxt.event.set()

    def _wait_for_token(self, m: _MThread) -> None:
        while True:
            if m.event.wait(self._STEAL_S):
                m.event.clear()
                return
            with self._lock:
                # the holder is parked in an uninstrumented wait (or
                # exited without dispatch finding us ready): if nothing
                # is running, steal the token so the suite doesn't hang
                if not any(
                    t.state == "running" for t in self._threads.values()
                ):
                    if m.state == "ready":
                        m.state = "running"
                        self.trace.append((m.name, "steal"))
                        return


def replay_strict_hint(seed: int) -> str:
    return f"StrictSched(seed={seed}) replays the identical schedule"
