"""``python -m parameter_server_tpu.analysis`` — run pslint, exit 1 on
findings. The same entry backs ``python -m parameter_server_tpu.cli
lint`` and the tier-1 clean-package test, so CI, the CLI and the tests
can never disagree about what clean means.

CI integration (ISSUE 8): ``--json`` emits machine-readable findings
(checker, file, line, message, plus ``id`` — the checker name a
``# psl: ignore[<id>]: <why>`` pragma takes); ``--baseline FILE`` gates
on *no NEW findings* against a recorded baseline instead of absolute
cleanliness, so a refactor-heavy PR (direction #1's replication churn)
can land with pre-existing debt visible but frozen. Baseline entries
match on (checker, file, message) — deliberately line-insensitive, so
edits above a finding don't churn the gate — and are counted as a
multiset, so introducing a SECOND instance of an already-baselined
finding still fails. ``--update-baseline`` rewrites the file from the
current findings (the reviewed way to accept or retire debt)."""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from pathlib import Path

from parameter_server_tpu.analysis import CHECKERS, PACKAGE_ROOT, analyze_package
from parameter_server_tpu.analysis.core import Finding


def finding_json(f: Finding) -> dict:
    return {
        "checker": f.checker,
        "file": f.path,
        "line": f.line,
        "message": f.message,
        # the pragma-able id: # psl: ignore[<id>]: <why> on f.line
        "id": f.checker,
    }


def _baseline_key(d: dict) -> tuple:
    return (d.get("checker"), d.get("file"), d.get("message"))


def load_baseline(path: Path) -> Counter:
    data = json.loads(path.read_text())
    entries = data["findings"] if isinstance(data, dict) else data
    return Counter(_baseline_key(d) for d in entries)


def new_vs_baseline(
    findings: list[Finding], baseline: Counter
) -> list[Finding]:
    """Findings beyond the baseline's multiset (oldest-seen instances of
    a repeated key are forgiven first — which instance of N identical
    findings is 'new' is unknowable without line anchoring)."""
    budget = Counter(baseline)
    out: list[Finding] = []
    for f in findings:
        k = (f.checker, f.path, f.message)
        if budget[k] > 0:
            budget[k] -= 1
        else:
            out.append(f)
    return out


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="pslint")
    p.add_argument(
        "--root", default=str(PACKAGE_ROOT),
        help="package directory to analyze (default: the installed "
        "parameter_server_tpu package)",
    )
    p.add_argument(
        "--checker", action="append", default=None,
        help="run only this checker (repeatable); default: all "
        f"({', '.join(CHECKERS)})",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    p.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="gate on no NEW findings vs this JSON baseline (missing "
        "file = empty baseline); combine with --update-baseline to "
        "(re)record it",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="write the current findings to --baseline and exit 0",
    )
    args = p.parse_args(argv)
    if args.update_baseline and not args.baseline:
        p.error("--update-baseline requires --baseline FILE")
    checkers = CHECKERS
    if args.checker:
        unknown = sorted(set(args.checker) - set(CHECKERS))
        if unknown:
            p.error(f"unknown checker(s) {unknown}; known: {sorted(CHECKERS)}")
        checkers = {n: CHECKERS[n] for n in args.checker}
    findings = analyze_package(args.root, checkers=checkers)
    if args.baseline and args.update_baseline:
        Path(args.baseline).write_text(json.dumps(
            {"findings": [finding_json(f) for f in findings]}, indent=1,
        ))
        print(
            f"pslint: baseline {args.baseline} updated "
            f"({len(findings)} finding(s))"
        )
        return 0
    gated = findings
    if args.baseline:
        bp = Path(args.baseline)
        baseline = load_baseline(bp) if bp.exists() else Counter()
        gated = new_vs_baseline(findings, baseline)
    if args.json:
        print(json.dumps([finding_json(f) for f in gated]))
    else:
        for f in gated:
            print(f.render())
        suffix = (
            f" ({len(gated)} NEW vs baseline {args.baseline})"
            if args.baseline else ""
        )
        print(
            f"pslint: {len(findings)} finding(s){suffix}, "
            f"{len(checkers)} checker(s) over {args.root}"
        )
    return 1 if gated else 0


if __name__ == "__main__":
    sys.exit(main())
