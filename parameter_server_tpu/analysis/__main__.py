"""``python -m parameter_server_tpu.analysis`` — run pslint, exit 1 on
findings. The same entry backs ``python -m parameter_server_tpu.cli
lint`` and the tier-1 clean-package test, so CI, the CLI and the tests
can never disagree about what clean means.

CI integration (ISSUE 8): ``--json`` emits machine-readable findings
(checker, file, line, message, plus ``id`` — the checker name a
``# psl: ignore[<id>]: <why>`` pragma takes); ``--baseline FILE`` gates
on *no NEW findings* against a recorded baseline instead of absolute
cleanliness, so a refactor-heavy PR (direction #1's replication churn)
can land with pre-existing debt visible but frozen. Baseline entries
match on (checker, file, message) — deliberately line-insensitive, so
edits above a finding don't churn the gate — and are counted as a
multiset, so introducing a SECOND instance of an already-baselined
finding still fails. ``--update-baseline`` rewrites the file from the
current findings (the reviewed way to accept or retire debt).

``--changed-only REF`` (pslint v3) narrows the REPORT to files that
differ from the git ref (diff + untracked): the ANALYSIS still runs
over the whole package — interprocedural summaries and the shared
dataflow fixpoint need every file — so a change whose finding
surfaces in an unchanged file is the one case the filter can hide,
and the full run stays the gate of record. When git is unavailable
the filter fails OPEN (everything reports): a silently empty lint
must never read as clean."""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from collections import Counter
from pathlib import Path

from parameter_server_tpu.analysis import (
    CHECKERS,
    PACKAGE_ROOT,
    _default_config,
    analyze_package,
    severity_of,
)
from parameter_server_tpu.analysis.core import Finding, PslintConfig


def finding_json(f: Finding, config: PslintConfig | None = None) -> dict:
    return {
        "checker": f.checker,
        "file": f.path,
        "line": f.line,
        "message": f.message,
        # the pragma-able id: # psl: ignore[<id>]: <why> on f.line
        "id": f.checker,
        # error | warn — tiered exit codes: any error gates exit 1,
        # warn-only runs exit 2, clean exits 0 ([tool.pslint] warn
        # extends the built-in warn set)
        "severity": severity_of(f.checker, config),
    }


def _baseline_key(d: dict) -> tuple:
    return (d.get("checker"), d.get("file"), d.get("message"))


def load_baseline(path: Path) -> Counter:
    data = json.loads(path.read_text())
    entries = data["findings"] if isinstance(data, dict) else data
    return Counter(_baseline_key(d) for d in entries)


def new_vs_baseline(
    findings: list[Finding], baseline: Counter
) -> list[Finding]:
    """Findings beyond the baseline's multiset (oldest-seen instances of
    a repeated key are forgiven first — which instance of N identical
    findings is 'new' is unknowable without line anchoring)."""
    budget = Counter(baseline)
    out: list[Finding] = []
    for f in findings:
        k = (f.checker, f.path, f.message)
        if budget[k] > 0:
            budget[k] -= 1
        else:
            out.append(f)
    return out


def changed_files(ref: str, root: Path) -> set[str] | None:
    """Package-relative paths of files changed vs ``ref`` (worktree
    diff, staged included, plus untracked); None when git can't answer
    — the caller must then skip filtering (fail open)."""
    root = root.resolve()

    def _git(*args: str) -> str:
        return subprocess.run(
            ["git", "-C", str(root), *args],
            capture_output=True, text=True, check=True, timeout=30,
        ).stdout

    try:
        top = Path(_git("rev-parse", "--show-toplevel").strip())
        listed = (
            _git("diff", "--name-only", ref, "--")
            # --full-name: toplevel-relative, like diff --name-only
            + _git("ls-files", "--others", "--exclude-standard",
                   "--full-name")
        )
    except (OSError, subprocess.SubprocessError):
        return None
    out: set[str] = set()
    for line in listed.splitlines():
        if not line.strip():
            continue
        try:
            out.add((top / line).resolve().relative_to(root).as_posix())
        except ValueError:
            continue  # changed, but outside the analyzed package
    return out


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="pslint")
    p.add_argument(
        "--root", default=str(PACKAGE_ROOT),
        help="package directory to analyze (default: the installed "
        "parameter_server_tpu package)",
    )
    p.add_argument(
        "--checker", action="append", default=None,
        help="run only this checker (repeatable); default: all "
        f"({', '.join(CHECKERS)})",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    p.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="gate on no NEW findings vs this JSON baseline (missing "
        "file = empty baseline); matching is LINE-INSENSITIVE — "
        "entries match on (checker, file, message) as a multiset, so "
        "edits above a finding never churn the gate but a second "
        "instance of a baselined finding still fails; combine with "
        "--update-baseline to (re)record it",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="write the current findings to --baseline and exit 0",
    )
    p.add_argument(
        "--changed-only", default=None, metavar="REF",
        help="report only findings in files changed vs this git ref "
        "(diff + untracked); the analysis itself still covers the "
        "whole package, so interprocedural summaries stay exact — "
        "this narrows the REPORT for fast pre-push iteration, it is "
        "not the gate of record. Fails open (reports everything) "
        "when git can't answer",
    )
    args = p.parse_args(argv)
    if args.update_baseline and not args.baseline:
        p.error("--update-baseline requires --baseline FILE")
    if args.update_baseline and args.changed_only:
        p.error(
            "--update-baseline records the FULL package's findings; "
            "drop --changed-only"
        )
    checkers = CHECKERS
    if args.checker:
        unknown = sorted(set(args.checker) - set(CHECKERS))
        if unknown:
            p.error(f"unknown checker(s) {unknown}; known: {sorted(CHECKERS)}")
        checkers = {n: CHECKERS[n] for n in args.checker}
    config = _default_config(Path(args.root))
    findings = analyze_package(args.root, checkers=checkers, config=config)
    scope = ""
    if args.changed_only:
        changed = changed_files(args.changed_only, Path(args.root))
        if changed is None:
            print(
                f"pslint: --changed-only {args.changed_only}: git "
                "unavailable — reporting ALL findings",
                file=sys.stderr,
            )
        else:
            findings = [f for f in findings if f.path in changed]
            scope = (
                f" [changed-only vs {args.changed_only}: "
                f"{len(changed)} file(s)]"
            )
    if args.baseline and args.update_baseline:
        Path(args.baseline).write_text(json.dumps(
            {"findings": [finding_json(f, config) for f in findings]},
            indent=1,
        ))
        print(
            f"pslint: baseline {args.baseline} updated "
            f"({len(findings)} finding(s))"
        )
        return 0
    gated = findings
    if args.baseline:
        bp = Path(args.baseline)
        baseline = load_baseline(bp) if bp.exists() else Counter()
        gated = new_vs_baseline(findings, baseline)
    errors = [
        f for f in gated if severity_of(f.checker, config) == "error"
    ]
    if args.json:
        print(json.dumps([finding_json(f, config) for f in gated]))
    else:
        for f in gated:
            sev = severity_of(f.checker, config)
            print(f"{f.render()} [{sev}]" if sev == "warn" else f.render())
        suffix = (
            f" ({len(gated)} NEW vs baseline {args.baseline})"
            if args.baseline else ""
        )
        print(
            f"pslint: {len(findings)} finding(s) "
            f"({len(errors)} error(s), {len(gated) - len(errors)} "
            f"warning(s) gating){suffix}, "
            f"{len(checkers)} checker(s) over {args.root}{scope}"
        )
    # tiered exit codes: errors are a hard 1, a warn-only run exits 2
    # (CI can gate on 1 while new analyses phase in), clean is 0
    return 1 if errors else (2 if gated else 0)


def check_main(argv: list[str] | None = None) -> int:
    """``cli check`` — psmc, the explicit-state protocol model checker
    (analysis/model.py over analysis/specs/), plus the spec<->code
    conformance diff. Exit 0 only when every selected spec model
    EXHAUSTS its bounded state space with zero invariant/liveness
    violations AND no model assumption has drifted from the
    AST-derived code tables; a violation prints its shortest
    counterexample as a replayable step list."""
    from parameter_server_tpu.analysis import load_package
    from parameter_server_tpu.analysis.conformance import conformance_diff
    from parameter_server_tpu.analysis.model import check
    from parameter_server_tpu.analysis.specs import SPECS

    p = argparse.ArgumentParser(prog="psmc")
    p.add_argument(
        "--spec", action="append", default=None,
        help="check only this protocol model (repeatable); default: "
        f"all ({', '.join(SPECS)})",
    )
    p.add_argument(
        "--max-states", type=int, default=200_000,
        help="BFS state cap; a capped run is reported incomplete and "
        "fails (verification demands exhaustion of the bounded space)",
    )
    p.add_argument(
        "--probe-seeds", type=int, default=0,
        help="when the cap is hit, continue with this many seeded "
        "random walks past the frontier (deterministic bug probing, "
        "not verification)",
    )
    p.add_argument(
        "--bug", default=None, metavar="KNOB",
        help="check the named seeded-bug VARIANT instead (requires "
        "exactly one --spec); exit 0 iff the checker produces a "
        "counterexample — how the suite's mutation coverage is "
        "demonstrated by hand",
    )
    p.add_argument(
        "--root", default=str(PACKAGE_ROOT),
        help="package directory the conformance diff derives code "
        "tables from",
    )
    p.add_argument(
        "--no-conformance", action="store_true",
        help="skip the spec<->code conformance diff (models only)",
    )
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    names = list(SPECS)
    if args.spec:
        unknown = sorted(set(args.spec) - set(SPECS))
        if unknown:
            p.error(f"unknown spec(s) {unknown}; known: {sorted(SPECS)}")
        names = list(args.spec)
    if args.bug is not None and len(names) != 1:
        p.error("--bug requires exactly one --spec")

    results = []
    for name in names:
        mod = SPECS[name]
        if args.bug is not None:
            if args.bug not in mod.BUGS:
                p.error(
                    f"spec {name!r} has no bug knob {args.bug!r}; "
                    f"known: {list(mod.BUGS)}"
                )
            spec = mod.make(bug=args.bug)
        else:
            spec = mod.tier1()
        results.append(check(
            spec, max_states=args.max_states,
            probe_seeds=args.probe_seeds,
        ))

    drift = []
    config = _default_config(Path(args.root))
    if args.bug is None and not args.no_conformance:
        drift = conformance_diff(load_package(Path(args.root)))

    if args.bug is not None:
        # mutation-coverage mode: the bug MUST be caught
        r = results[0]
        ok = r.violation is not None
        if args.json:
            print(json.dumps({"bug": args.bug, "caught": ok,
                              "result": r.summary()}))
        elif ok:
            print(f"psmc: seeded bug {args.bug!r} caught:\n"
                  + r.violation.render())
        else:
            print(f"psmc: seeded bug {args.bug!r} NOT caught "
                  f"({r.states} states) — the model lost its teeth")
        return 0 if ok else 1

    ok = all(r.ok and r.complete for r in results) and not drift
    if args.json:
        print(json.dumps({
            "ok": ok,
            "specs": [r.summary() for r in results],
            "conformance": [finding_json(f, config) for f in drift],
        }))
    else:
        for r in results:
            status = (
                "verified" if r.ok and r.complete
                else "INCOMPLETE (state cap hit)" if r.ok
                else "VIOLATION"
            )
            print(
                f"psmc: {r.spec:<14} {r.states:>7} states "
                f"{r.transitions:>8} transitions depth {r.depth:>3}  "
                f"{status}"
            )
            if r.violation is not None:
                print(r.violation.render())
        for f in drift:
            print(f.render())
        verdict = "all protocols verified at these bounds" if ok else (
            "NOT verified — fix the model or the code, together"
        )
        print(
            f"psmc: {len(results)} spec(s), {len(drift)} conformance "
            f"drift finding(s): {verdict}"
        )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
