"""``python -m parameter_server_tpu.analysis`` — run pslint, exit 1 on
findings. The same entry backs ``python -m parameter_server_tpu.cli
lint`` and the tier-1 clean-package test, so CI, the CLI and the tests
can never disagree about what clean means."""

from __future__ import annotations

import argparse
import json
import sys

from parameter_server_tpu.analysis import CHECKERS, PACKAGE_ROOT, analyze_package


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="pslint")
    p.add_argument(
        "--root", default=str(PACKAGE_ROOT),
        help="package directory to analyze (default: the installed "
        "parameter_server_tpu package)",
    )
    p.add_argument(
        "--checker", action="append", default=None,
        help="run only this checker (repeatable); default: all "
        f"({', '.join(CHECKERS)})",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    args = p.parse_args(argv)
    checkers = CHECKERS
    if args.checker:
        unknown = sorted(set(args.checker) - set(CHECKERS))
        if unknown:
            p.error(f"unknown checker(s) {unknown}; known: {sorted(CHECKERS)}")
        checkers = {n: CHECKERS[n] for n in args.checker}
    findings = analyze_package(args.root, checkers=checkers)
    if args.json:
        print(json.dumps([f.__dict__ for f in findings]))
    else:
        for f in findings:
            print(f.render())
        print(
            f"pslint: {len(findings)} finding(s), "
            f"{len(checkers)} checker(s) over {args.root}"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
