"""Checker ``wireproto``: the binary-header codec, cmd-id table, feature
adverts and reply decoration stay mutually consistent — derived from the
AST, checked as dataflow facts, never hand-listed.

The wire module (parallel/control.py) carries four coupled inventories
that ISSUE 4/6/7 grew one PR at a time:

1. **slot tables** — ``_encode_bin_header`` packs header fields under
   ``_BF_*``/``_BF2_*`` flag bits; ``_decode_bin_header`` unpacks them.
   A field encoded under one flag and decoded under another (or encoded
   and never decoded) is silent wire corruption that only a mixed-version
   cluster ever exercises. The checker derives the *field -> flags*
   table from each side's branch structure and diffs them.
2. **version gating** — v1's flag inventory is FROZEN wire contract
   (this checker embeds it, exactly the append-only rule the codec
   comments promise). Any flag beyond v1 must be OR-ed into the version
   mask (``*_V2_MASK``) the encoder stamps the version byte from;
   otherwise a frame using the new slot ships stamped ``version=1`` and
   a v1 peer misparses it. A v1 flag *in* the mask is the inverse bug:
   every ordinary frame gets stamped v2 and old peers reject it.
3. **cmd ids** — ``_CMD_IDS`` built from an enumerated name tuple must
   not repeat a name: dict construction dedups silently, which SHIFTS
   every later id and breaks the append-only id contract with deployed
   peers. (A literal dict form is checked for duplicate ids directly.)
4. **feature adverts** — ``features=`` literals at ``RpcServer(...)``
   sites are what servers can ack, ``features=`` at ``RpcClient(...)``
   sites (resolved one hop through ``self.<attr>`` assignments) are what
   clients advertise. A feature only one side knows is dead negotiation:
   the client silently never leaves its fallback path, or the server
   acks something nobody sends.
5. **reply decoration** — every reply queued to the wire must flow
   through the connection's ``decorated()`` helper (seq echo, ``_bh``
   codec ack, ``_feat`` ack), on the deferred and cached paths included.
   This is checked as a dataflow fact (analysis/dataflow.py): the first
   argument of every ``queue_reply(...)`` call must carry the provenance
   tag of a ``decorated(...)`` result — not a literal-name whitelist,
   so a reply that takes a detour through a local variable still counts
   and a raw dict sneaking in still fails.
"""

from __future__ import annotations

import ast

from parameter_server_tpu.analysis.core import Finding, PackageIndex
from parameter_server_tpu.analysis.dataflow import FlowPolicy, Tags
from parameter_server_tpu.analysis.flowrun import (
    flow_policy,
    register_flow_policy,
)

_ENCODE_FN = "_encode_bin_header"
_DECODE_FN = "_decode_bin_header"

#: v1 flag inventory — FROZEN wire contract (the append-only rule). A
#: checker hardcoding a wire-frozen table is not a drifting hand-list:
#: changing v1 is exactly the event that must fail the build.
V1_FLAGS = frozenset({
    "_BF_CID", "_BF_SEQ", "_BF_RSEQ", "_BF_EXTRA", "_BF_OK_TRUE",
    "_BF_OK_FALSE", "_BF_ZIP", "_BF_CMD_STR",
    "_BF2_WORKER", "_BF2_SIG", "_BF2_CODEC", "_BF2_NEED_KEYS",
    "_BF2_TRANSIENT",
})


#: the flag-constant families across header generations: v1/v2 bits
#: live in the two original flag bytes; ``_BF3_*`` bits ride the
#: appended flags3 byte (version 3, the freshness slots) and are gated
#: by the ``_BVERSION3`` stamp instead of the v2 mask
_FLAG_PREFIXES = ("_BF_", "_BF2_", "_BF3_")


def _flag_names(node: ast.AST) -> set[str]:
    return {
        sub.id
        for sub in ast.walk(node)
        if isinstance(sub, ast.Name)
        and sub.id.startswith(_FLAG_PREFIXES)
        and not sub.id.endswith("_MASK")
    }


def _field_in_test(test: ast.AST) -> str | None:
    """``k == "<field>"`` (possibly inside an ``and`` chain) -> field."""
    for sub in ast.walk(test):
        if not isinstance(sub, ast.Compare) or len(sub.ops) != 1:
            continue
        if not isinstance(sub.ops[0], ast.Eq):
            continue
        left, right = sub.left, sub.comparators[0]
        if (
            isinstance(left, ast.Name)
            and left.id == "k"
            and isinstance(right, ast.Constant)
            and isinstance(right.value, str)
        ):
            return right.value
    return None


def _walk_own_body(if_node: ast.If):
    """Every node in an If's body (its elif chain lives in ``orelse``
    and is visited as its own If by the caller's ast.walk)."""
    for stmt in if_node.body:
        yield from ast.walk(stmt)


def encode_table(fndef: ast.FunctionDef) -> dict[str, frozenset[str]]:
    """field -> flag names OR-ed while encoding it."""
    out: dict[str, set[str]] = {}
    for node in ast.walk(fndef):
        if not isinstance(node, ast.If):
            continue
        field = _field_in_test(node.test)
        if field is None:
            continue
        flags: set[str] = set()
        for sub in _walk_own_body(node):
            if isinstance(sub, ast.AugAssign) and isinstance(
                sub.op, ast.BitOr
            ):
                flags |= _flag_names(sub.value)
        out.setdefault(field, set()).update(flags)
    return {f: frozenset(s) for f, s in out.items()}


def decode_table(fndef: ast.FunctionDef) -> dict[str, frozenset[str]]:
    """field -> flag names guarding its ``h["<field>"] = ...`` decode."""
    out: dict[str, set[str]] = {}
    for node in ast.walk(fndef):
        if not isinstance(node, ast.If):
            continue
        flags = _flag_names(node.test)
        if not flags:
            continue
        for sub in _walk_own_body(node):
            if not isinstance(sub, ast.Assign):
                continue
            for t in sub.targets:
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.slice, ast.Constant)
                    and isinstance(t.slice.value, str)
                ):
                    out.setdefault(t.slice.value, set()).update(flags)
    return {f: frozenset(s) for f, s in out.items()}


def _module_flags(tree: ast.Module) -> dict[str, int]:
    """Every module-level ``_BF*`` integer flag constant -> lineno
    (aggregate masks and derived expressions excluded)."""
    out: dict[str, int] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if not (
                isinstance(t, ast.Name)
                and t.id.startswith(_FLAG_PREFIXES)
                and not t.id.endswith("_MASK")
            ):
                continue
            if isinstance(node.value, ast.Constant):
                out[t.id] = node.lineno
    return out


def _mask_members(tree: ast.Module) -> tuple[set[str], int] | None:
    """Members of the ``*_V2_MASK`` OR-chain (None when absent)."""
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id.endswith("_V2_MASK"):
                return _flag_names(node.value), node.lineno
    return None


def _check_codec_tables(
    f, enc: ast.FunctionDef, dec: ast.FunctionDef, out: list[Finding]
) -> None:
    et = encode_table(enc)
    dt = decode_table(dec)
    for field in sorted(set(et) | set(dt)):
        ef, df = et.get(field), dt.get(field)
        if ef is None:
            out.append(Finding(
                "wireproto", f.relpath, dec.lineno,
                f"binary header field {field!r} is decoded but never "
                "encoded — a slot no sender can fill is dead layout (or "
                "the encoder branch was dropped in a refactor)",
            ))
        elif df is None:
            out.append(Finding(
                "wireproto", f.relpath, enc.lineno,
                f"binary header field {field!r} is encoded but never "
                "decoded — every peer silently drops it off the wire",
            ))
        elif ef != df:
            out.append(Finding(
                "wireproto", f.relpath, enc.lineno,
                f"binary header field {field!r} is encoded under "
                f"{sorted(ef)} but decoded under {sorted(df)} — the two "
                "sides parse different layouts (silent corruption in "
                "any frame carrying the field)",
            ))
    # version gating: flags beyond the frozen v1 inventory must ride the
    # v2 mask the encoder stamps the version byte from — except the
    # ``_BF3_*`` family, which lives in the appended flags3 byte and is
    # gated by the _BVERSION3 stamp instead (checked below)
    flags = _module_flags(f.tree)
    mask = _mask_members(f.tree)
    bf3 = {n for n in flags if n.startswith("_BF3_")}
    if bf3:
        for side, fn in (("encoder", enc), ("decoder", dec)):
            if not any(
                isinstance(sub, ast.Name) and sub.id == "_BVERSION3"
                for sub in ast.walk(fn)
            ):
                n = sorted(bf3)[0]
                out.append(Finding(
                    "wireproto", f.relpath, flags[n],
                    f"flag {n} rides the flags3 byte but the {side} "
                    "never consults _BVERSION3 — v3-slot frames would "
                    "ship unstamped (or the flags3 byte would be "
                    "misparsed as a v1/v2 slot)",
                ))
    extra = {n for n in flags if n not in V1_FLAGS} - bf3
    if extra and mask is None:
        n = sorted(extra)[0]
        out.append(Finding(
            "wireproto", f.relpath, flags[n],
            f"flag {n} extends the frozen v1 layout but the module has "
            "no *_V2_MASK to version-gate it — frames using the new "
            "slot would ship stamped version=1 and v1 peers misparse "
            "them",
        ))
    elif mask is not None:
        members, mline = mask
        for n in sorted(extra - members):
            out.append(Finding(
                "wireproto", f.relpath, flags[n],
                f"flag {n} extends the frozen v1 layout but is missing "
                "from the version mask — a frame using this slot is "
                "stamped version=1 and a v1 peer misparses it (flag "
                "evolution is append-only AND gated)",
            ))
        for n in sorted(members & V1_FLAGS):
            out.append(Finding(
                "wireproto", f.relpath, mline,
                f"v1 flag {n} is in the version mask — every ordinary "
                "frame using it gets stamped v2 and old peers reject "
                "frames they used to decode",
            ))
        if not any(
            isinstance(sub, ast.Name) and sub.id.endswith("_V2_MASK")
            for sub in ast.walk(enc)
        ):
            out.append(Finding(
                "wireproto", f.relpath, enc.lineno,
                "the encoder never consults the version mask when "
                "stamping the version byte — v2-slot frames ship as v1",
            ))


def _check_cmd_ids(index: PackageIndex, out: list[Finding]) -> None:
    for f in index.files:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == "_CMD_IDS"
                for t in node.targets
            ):
                continue
            v = node.value
            if isinstance(v, ast.DictComp):
                # {c: i + 1 for i, c in enumerate((...))}: a duplicated
                # name dedups in the dict and SHIFTS every later id
                names = [
                    s.value
                    for s in ast.walk(v.generators[0].iter)
                    if isinstance(s, ast.Constant)
                    and isinstance(s.value, str)
                ]
                seen: set[str] = set()
                for n in names:
                    if n in seen:
                        out.append(Finding(
                            "wireproto", f.relpath, node.lineno,
                            f"_CMD_IDS name tuple repeats {n!r} — dict "
                            "construction dedups it and shifts every "
                            "later compact id, breaking the append-only "
                            "id contract with deployed peers",
                        ))
                    seen.add(n)
            elif isinstance(v, ast.Dict):
                ids: dict[int, str] = {}
                for k, val in zip(v.keys, v.values):
                    if not (
                        isinstance(k, ast.Constant)
                        and isinstance(val, ast.Constant)
                        and isinstance(val.value, int)
                    ):
                        continue
                    if val.value in ids:
                        out.append(Finding(
                            "wireproto", f.relpath, node.lineno,
                            f"_CMD_IDS maps both {ids[val.value]!r} and "
                            f"{k.value!r} to id {val.value} — two "
                            "commands on one wire id decode "
                            "interchangeably",
                        ))
                    else:
                        ids[val.value] = k.value
                    if val.value == 0:
                        out.append(Finding(
                            "wireproto", f.relpath, node.lineno,
                            f"_CMD_IDS gives {k.value!r} id 0 — 0 is "
                            "the reserved absent/unknown sentinel",
                        ))


def _features_in(expr: ast.AST) -> set[str]:
    return {
        s.value
        for s in ast.walk(expr)
        if isinstance(s, ast.Constant) and isinstance(s.value, str)
    }


def _ctor_features(
    index: PackageIndex, ctor_suffix: str
) -> dict[str, tuple[str, int]]:
    """feature -> first (relpath, line) advertising/acking it at a
    ``*RpcServer(...)`` / ``*RpcClient(...)`` construction site. A
    ``features=self.<attr>`` kwarg resolves one hop through the
    enclosing class's assignments to that attribute."""
    out: dict[str, tuple[str, int]] = {}
    for f in index.files:
        for cls in ast.walk(f.tree):
            if not isinstance(cls, (ast.ClassDef, ast.Module)):
                continue
            for node in ast.walk(cls):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                name = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else ""
                )
                if not name.endswith(ctor_suffix):
                    continue
                for kw in node.keywords:
                    if kw.arg != "features":
                        continue
                    feats = _features_in(kw.value)
                    if not feats and isinstance(cls, ast.ClassDef):
                        # one-hop resolution: features=self._features
                        attr = None
                        if (
                            isinstance(kw.value, ast.Attribute)
                            and isinstance(kw.value.value, ast.Name)
                            and kw.value.value.id == "self"
                        ):
                            attr = kw.value.attr
                        if attr is not None:
                            for sub in ast.walk(cls):
                                if isinstance(sub, ast.Assign) and any(
                                    isinstance(t, ast.Attribute)
                                    and t.attr == attr
                                    for t in sub.targets
                                ):
                                    feats |= _features_in(sub.value)
                    for feat in feats:
                        out.setdefault(feat, (f.relpath, node.lineno))
    return out


def _check_features(index: PackageIndex, out: list[Finding]) -> None:
    # the generic RpcServer/RpcClient definitions themselves take a
    # ``features`` parameter — only CONSTRUCTION sites advertise
    srv = _ctor_features(index, "RpcServer")
    cli = _ctor_features(index, "RpcClient")
    if not srv and not cli:
        return
    for feat in sorted(set(cli) - set(srv)):
        rel, line = cli[feat]
        out.append(Finding(
            "wireproto", rel, line,
            f"clients advertise wire feature {feat!r} but no RpcServer "
            "construction site acks it — the negotiation can never "
            "succeed, so the feature's fast path is dead code",
        ))
    for feat in sorted(set(srv) - set(cli)):
        rel, line = srv[feat]
        out.append(Finding(
            "wireproto", rel, line,
            f"servers ack wire feature {feat!r} but no RpcClient "
            "construction site advertises it — nobody can negotiate it",
        ))


TAG_DECORATED = "decorated"


class _DecorationPolicy(FlowPolicy):
    """Dataflow: a value returned by ``decorated(...)`` carries
    TAG_DECORATED; every ``queue_reply(first_arg, ...)`` must receive a
    carrier (directly or through any number of assignments)."""

    def __init__(self, modules: set[str]):
        self._modules = modules  # relpaths defining both helpers
        self._relpath = ""
        self.findings: list[tuple[str, int]] = []
        self._seen: set[tuple[str, int]] = set()

    def owns(self, tag: str) -> bool:
        return tag == TAG_DECORATED

    def begin_function(
        self, relpath: str, cls_name: str | None, fn_name: str
    ) -> None:
        self._relpath = relpath

    def call_result(
        self, call: ast.Call, recv_tags: Tags, arg_tags: list[Tags]
    ) -> Tags:
        fn = call.func
        if isinstance(fn, ast.Name) and fn.id == "decorated":
            return frozenset({TAG_DECORATED})
        return super().call_result(call, recv_tags, arg_tags)

    def on_call(self, call, arg_tags, held, eval_expr) -> None:
        if self._relpath not in self._modules:
            return
        fn = call.func
        if not (isinstance(fn, ast.Name) and fn.id == "queue_reply"):
            return
        if not call.args:
            return
        if TAG_DECORATED not in arg_tags[0]:
            key = (self._relpath, call.lineno)
            if key not in self._seen:
                self._seen.add(key)
                self.findings.append(key)


def _decoration_factory(index: PackageIndex) -> _DecorationPolicy | None:
    modules: set[str] = set()
    for f in index.files:
        names = {
            n.name
            for n in ast.walk(f.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        if "decorated" in names and "queue_reply" in names:
            modules.add(f.relpath)
    if not modules:
        return None
    return _DecorationPolicy(modules)


register_flow_policy("wireproto-decoration", _decoration_factory)


def _check_decoration(index: PackageIndex, out: list[Finding]) -> None:
    policy = flow_policy(index, "wireproto-decoration")
    if policy is None:  # no module defines both helpers
        return
    assert isinstance(policy, _DecorationPolicy)
    for rel, line in sorted(policy.findings):
        out.append(Finding(
            "wireproto", rel, line,
            "reply queued without flowing through decorated(): the seq "
            "echo / _bh codec ack / _feat feature ack are lost on this "
            "path — a pipelined client can't match the reply and "
            "negotiation silently stalls (deferred and cached replies "
            "must decorate too)",
        ))


def check_wireproto(index: PackageIndex) -> list[Finding]:
    out: list[Finding] = []
    for f in index.files:
        enc = dec = None
        for node in ast.walk(f.tree):
            if isinstance(node, ast.FunctionDef):
                if node.name == _ENCODE_FN:
                    enc = node
                elif node.name == _DECODE_FN:
                    dec = node
        if enc is not None and dec is not None:
            _check_codec_tables(f, enc, dec, out)
    _check_cmd_ids(index, out)
    _check_features(index, out)
    _check_decoration(index, out)
    return out
