"""Runtime lock-order witness (the dynamic complement of ``lock-order``).

Armed chaos-style — opt-in via ``PS_LOCK_WITNESS=1`` (or an explicit
``install()`` from the test harness) — the witness wraps
``threading.Lock`` / ``RLock`` / ``Condition`` CONSTRUCTION so that every
lock created by package code carries its construction site as its
identity (``parallel/control.py:1130``), then records the actual
acquisition order each thread takes. Acquiring lock B while holding lock
A adds the edge A -> B to a process-global order graph, seeded with the
edges the static analyzer derived (analysis/lockgraph.py, translated to
construction sites); an acquisition that would close a cycle — i.e. an
inversion of an order the process (or the static analysis) has already
witnessed — raises :class:`LockOrderViolation` BEFORE blocking, naming
the cycle. That converts a probabilistic deadlock hang into a
deterministic stack trace at the first inverted acquisition, which is
the FreeBSD WITNESS idea rebuilt for this codebase.

Scope: only locks constructed from ``parameter_server_tpu`` source files
are instrumented (stdlib internals — queue, concurrent.futures,
threading.Event — keep raw locks), and same-site pairs are exempt (two
instances of one class are peers, not an ordering).
"""

from __future__ import annotations

import os
import sys
import threading

ENV_VAR = "PS_LOCK_WITNESS"

_PKG_MARKER = os.sep + "parameter_server_tpu" + os.sep


class LockOrderViolation(RuntimeError):
    """An acquisition inverted an already-witnessed lock order."""


class _Graph:
    """Process-global acquisition-order graph (site-name nodes)."""

    def __init__(self, raw_lock_cls):
        self._lock = raw_lock_cls()
        self._adj: dict[str, set[str]] = {}

    def seed(self, edges) -> None:
        """Seed statically-derived edges THROUGH the cycle check (in
        deterministic order): if the static graph itself contains a
        cycle — e.g. one a maintainer pragma-suppressed past the
        lock-order checker — only the first direction seeds, so the
        graph stays acyclic and a runtime acquisition taking the other
        direction still raises instead of hitting the already-witnessed
        fast path."""
        for a, b in sorted(edges):
            if a != b:
                self.check_and_add(a, b)  # a returned cycle: edge skipped

    def edges(self) -> set[tuple[str, str]]:
        with self._lock:
            return {(a, b) for a, bs in self._adj.items() for b in bs}

    def clear(self) -> None:
        with self._lock:
            self._adj.clear()

    def check_and_add(self, held: str, acquiring: str) -> list[str] | None:
        """Record ``held -> acquiring``; returns a cycle path when the
        reverse direction is already reachable (the inversion)."""
        with self._lock:
            if acquiring in self._adj.get(held, ()):
                return None  # edge already witnessed (and cycle-checked)
            # BFS: acquiring ~> held already known?
            if acquiring in self._adj:
                parents: dict[str, str] = {}
                frontier = [acquiring]
                seen = {acquiring}
                found = False
                while frontier and not found:
                    nxt: list[str] = []
                    for n in frontier:
                        for m in self._adj.get(n, ()):  # noqa: B007
                            if m in seen:
                                continue
                            parents[m] = n
                            if m == held:
                                found = True
                                break
                            seen.add(m)
                            nxt.append(m)
                        if found:
                            break
                    frontier = nxt
                if found:
                    path = [held]
                    while path[-1] != acquiring:
                        path.append(parents.get(path[-1], acquiring))
                    return path[::-1] + [acquiring]
            self._adj.setdefault(held, set()).add(acquiring)
            return None


class _ThreadState(threading.local):
    def __init__(self) -> None:
        self.counts: dict[int, int] = {}  # id(wrapper) -> recursion depth
        self.stack: list["WitnessLock"] = []  # first-acquisition order


_tls = _ThreadState()
_graph: _Graph | None = None
_orig: dict[str, object] = {}
_installs = 0


class WitnessLock:
    """Order-witnessing proxy around a raw Lock/RLock. Duck-compatible
    with the lock API (``with``, acquire/release, Condition's
    ``_is_owned``/``_release_save`` forwarding via ``__getattr__``)."""

    def __init__(self, inner, name: str):
        self._psl_inner = inner
        self._psl_name = name

    def _psl_check(self) -> None:
        g = _graph
        if g is None:
            return
        name = self._psl_name
        for h in _tls.stack:
            if h._psl_name == name:
                continue  # peers of one site: not an ordering
            cycle = g.check_and_add(h._psl_name, name)
            if cycle is not None:
                raise LockOrderViolation(
                    f"lock order inversion: thread "
                    f"{threading.current_thread().name} acquires "
                    f"{name} while holding {h._psl_name}, but the "
                    "witnessed order is " + " -> ".join(cycle)
                )

    def acquire(self, blocking: bool = True, timeout: float = -1):
        first = _tls.counts.get(id(self), 0) == 0
        if first:
            # check (and record) BEFORE blocking: an inversion raises
            # with a stack trace instead of deadlocking probabilistically
            self._psl_check()
        got = self._psl_inner.acquire(blocking, timeout)
        if got:
            _tls.counts[id(self)] = _tls.counts.get(id(self), 0) + 1
            if first:
                _tls.stack.append(self)
        return got

    def release(self) -> None:
        self._psl_inner.release()
        c = _tls.counts.get(id(self), 0)
        if c <= 1:
            _tls.counts.pop(id(self), None)
            try:
                _tls.stack.remove(self)
            except ValueError:  # released by a thread that never acquired
                pass
        else:
            _tls.counts[id(self)] = c - 1

    def __enter__(self) -> "WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self):
        return self._psl_inner.locked()

    def __getattr__(self, name: str):
        return getattr(self._psl_inner, name)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<WitnessLock {self._psl_name} of {self._psl_inner!r}>"


def wrap(inner, name: str) -> WitnessLock:
    """Explicitly wrap a raw lock (tests; ad-hoc instrumentation)."""
    return WitnessLock(inner, name)


def _caller_site() -> str | None:
    f = sys._getframe(2)  # factory -> patched ctor -> caller
    fn = f.f_code.co_filename
    i = fn.rfind(_PKG_MARKER)
    if i < 0:
        return None
    rel = fn[i + len(_PKG_MARKER):].replace(os.sep, "/")
    if rel.startswith("analysis/"):
        return None  # the witness must not instrument itself
    return f"{rel}:{f.f_lineno}"


def _lock_factory():
    site = _caller_site()
    inner = _orig["Lock"]()
    return WitnessLock(inner, site) if site else inner


def _rlock_factory():
    site = _caller_site()
    inner = _orig["RLock"]()
    return WitnessLock(inner, site) if site else inner


def _cond_factory(lock=None):
    # instrument the default lock of package-constructed Conditions: the
    # Condition delegates acquire/release to it, so `with cv:` records
    # through the wrapper while cv.wait()'s internal release/re-acquire
    # (which never changes what the thread holds overall) stays raw
    if lock is None:
        site = _caller_site()
        if site is not None:
            lock = WitnessLock(_orig["RLock"](), site)
    return _orig["Condition"](lock) if lock is not None else _orig["Condition"]()


def _static_site_edges() -> set[tuple[str, str]]:
    """The statically-derived order, translated from lock KEYS
    (``RpcClient._cv``) to construction sites (``parallel/control.py:N``)
    so runtime identities match."""
    from parameter_server_tpu.analysis import build_lock_graph, load_package

    lg = build_lock_graph(load_package())
    out: set[tuple[str, str]] = set()
    for (a, b) in lg.edges:
        for ap, al in lg.sites.get(a, ()):  # noqa: B007
            for bp, bl in lg.sites.get(b, ()):
                out.add((f"{ap}:{al}", f"{bp}:{bl}"))
    return out


def install(static: bool = True) -> None:
    """Arm the witness: patch the threading lock constructors and seed
    the order graph with the static analyzer's edges. Idempotent;
    nested installs are reference-counted."""
    global _graph, _installs
    _installs += 1
    if _installs > 1:
        return
    _orig["Lock"] = threading.Lock
    _orig["RLock"] = threading.RLock
    _orig["Condition"] = threading.Condition
    _graph = _Graph(_orig["Lock"])
    if static:
        try:
            _graph.seed(_static_site_edges())
        except Exception:  # pragma: no cover - analyzer must never arm-fail
            pass
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _cond_factory


def uninstall() -> None:
    """Disarm: restore the raw constructors. Locks already wrapped keep
    working (the wrapper simply stops finding a graph to record into)."""
    global _graph, _installs
    if _installs == 0:
        return
    _installs -= 1
    if _installs > 0:
        return
    threading.Lock = _orig["Lock"]
    threading.RLock = _orig["RLock"]
    threading.Condition = _orig["Condition"]
    _graph = None


def installed() -> bool:
    return _installs > 0


def observed_edges() -> set[tuple[str, str]]:
    """The current order graph (static seed + runtime observations)."""
    return _graph.edges() if _graph is not None else set()


def maybe_install_from_env() -> bool:
    """The chaos-style opt-in: arm iff ``PS_LOCK_WITNESS`` is truthy."""
    if os.environ.get(ENV_VAR, "") not in ("", "0"):
        install()
        return True
    return False
