"""Eraser-style lockset race witness (``PS_RACE_WITNESS=1``) — the
data-race complement of the lock-order witness (PR 5) and the seeded
interleaving explorer (PR 8).

The witness catches wrong lock ORDERS; the explorer MAKES unlikely
schedules happen; neither notices a shared field that is simply
accessed with no lock at all — the bug class Eraser's lockset
discipline catches without needing the racy schedule to fire. Armed,
this module:

1. wraps ``threading.Lock``/``RLock``/``Condition`` CONSTRUCTION in
   package modules (composing over whatever factory is current — an
   armed witness or explorer keeps working underneath) so each thread's
   currently-held lock set is tracked;
2. instruments REGISTERED shared objects (``track(obj, fields)`` —
   no-op unless armed): the named fields become observed attributes,
   and every read/write records the accessing thread and its held
   locks;
3. runs the lockset state machine per (object, field): first thread
   owns the field exclusively; once a second thread touches it, the
   candidate lockset is the intersection of locks held at every
   access — when the intersection goes EMPTY on a write/write or
   write/read pair from different threads, that pair is reported with
   BOTH stacks (the current access's and the remembered conflicting
   one).

Reports collect in ``reports()`` (and print once to stderr); they are
diagnoses, not exceptions — an armed chaos run finishes and THEN
asserts ``reports() == []``, the acceptance form the serving
chaos-coherence test runs under.

Registered objects (the registration hooks live in the owning
constructors, zero-cost disarmed): the quantized-push residual
accumulator (``ServerHandle._residual``/``_res_map``/``_res_vdim``
under ``_res_lock``), the server's single-flight encode-cache byte
budget (``ShardServer._enc_bytes`` under ``_enc_lock``), the durable
push ledger reference (``ShardServer._applied_push`` under the apply
lock), the per-key heat sketch (``KeyHeatSketch._t``/``_n``/``_hot``
under its lock), the client key cache's invalidation generation
(``ClientKeyCache._gen``) and the pipelined client's in-flight window
(``RpcClient._pending``/``_eff_window`` under ``_cv``).

Scope mirrors the sibling witnesses: only package-constructed locks are
instrumented, ``analysis/`` itself is exempt, and only instances
explicitly registered while armed are observed (an instance built
before arming keeps raw attributes — its locks would be raw too, and
observing it would report phantom races).
"""

from __future__ import annotations

import os
import sys
import threading
import traceback
from dataclasses import dataclass, field

ENV_VAR = "PS_RACE_WITNESS"

_PKG_MARKER = os.sep + "parameter_server_tpu" + os.sep

_MARKER = "_psr_tracked_name"  # instance-dict opt-in marker

_installs = 0
_orig: dict[str, object] = {}
_lock = threading.Lock()  # guards _fields/_reports/_instrumented
_reports: list["RaceReport"] = []
#: class -> fields instrumented (descriptors installed)
_instrumented: dict[type, set[str]] = {}
#: (id(obj), field) -> _FieldState
_fields: dict[tuple[int, str], "_FieldState"] = {}


class _Tls(threading.local):
    def __init__(self) -> None:
        self.held: list[int] = []  # id() of each held LocksetLock


_tls = _Tls()


@dataclass
class RaceReport:
    obj: str
    attr: str
    kind: str  # write/write | read/write
    thread_a: str
    stack_a: list[str]
    thread_b: str
    stack_b: list[str]

    def render(self) -> str:
        a = "".join(self.stack_a).rstrip()
        b = "".join(self.stack_b).rstrip()
        return (
            f"RACE {self.kind} on {self.obj}.{self.attr}: no common "
            f"lock across threads\n"
            f"--- {self.thread_a} ---\n{a}\n"
            f"--- {self.thread_b} ---\n{b}"
        )


@dataclass
class _Access:
    thread: str
    ident: int
    write: bool
    stack: list[str] = field(default_factory=list)


@dataclass
class _FieldState:
    name: str  # "<ClassName#1a2b>" registration name
    first_thread: int | None = None
    shared: bool = False
    lockset: frozenset[int] | None = None  # candidate set once shared
    last_write: _Access | None = None
    last_read: _Access | None = None
    reported: bool = False


def _stack() -> list[str]:
    # the witness's own frames (this helper, the recorder and the
    # descriptor __get__/__set__) are noise — the access site is last
    return traceback.format_stack(limit=10)[:-3]


def _record(obj, attr: str, write: bool) -> None:
    key = (id(obj), attr)
    # peek (GIL-atomic dict read) and format the stack OUTSIDE the
    # global lock: formatting is the expensive part of every tracked
    # access and must not serialize all threads; once a field has
    # reported, further bookkeeping on it buys nothing
    st0 = _fields.get(key)
    if st0 is None or st0.reported:
        return
    me = threading.get_ident()
    held = frozenset(_tls.held)
    stack = _stack()
    with _lock:
        st = _fields.get(key)
        if st is None or st.reported:
            return  # untracked instance (marker raced an uninstall)
        if st.first_thread is None:
            st.first_thread = me
        if not st.shared:
            if st.first_thread == me:
                # exclusive phase: remember accesses for later pairing,
                # but no lockset judgment yet (init writes are benign)
                acc = _Access(
                    threading.current_thread().name, me, write, stack
                )
                if write:
                    st.last_write = acc
                else:
                    st.last_read = acc
                return
            st.shared = True
            st.lockset = held
        else:
            st.lockset = (
                held if st.lockset is None else st.lockset & held
            )
        acc = _Access(threading.current_thread().name, me, write, stack)
        # the remembered half of a report must be a CONFLICTING access
        # from a DIFFERENT thread — pairing with this thread's own
        # earlier access would render one thread on both sides and send
        # the reader to a non-racing site. Prefer the write (write/write
        # beats read/write when both are available).
        others = [
            a for a in (st.last_write, st.last_read)
            if a is not None and a.ident != me and (write or a.write)
        ]
        if not st.lockset and others:
            other = others[0]
            kind = "write/write" if write and other.write else "read/write"
            st.reported = True
            rep = RaceReport(
                st.name, attr, kind,
                acc.thread, acc.stack,
                other.thread, other.stack,
            )
            _reports.append(rep)
            print(
                f"[racewitness] {rep.render()}", file=sys.stderr
            )
        if write:
            st.last_write = acc
        else:
            st.last_read = acc


class _RaceField:
    """Data descriptor observing one tracked attribute. Values live in
    the instance dict under the REAL attribute name, so uninstalling
    (deleting the descriptor) leaves every instance's state intact."""

    def __init__(self, name: str, prev: object | None):
        self._name = name
        self._prev = prev  # shadowed class attribute (restored on uninstall)

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        try:
            v = obj.__dict__[self._name]
        except KeyError:
            if self._prev is not None:
                return self._prev
            raise AttributeError(self._name) from None
        if obj.__dict__.get(_MARKER) is not None:
            _record(obj, self._name, write=False)
        return v

    def __set__(self, obj, value) -> None:
        if obj.__dict__.get(_MARKER) is not None:
            _record(obj, self._name, write=True)
        obj.__dict__[self._name] = value

    def __delete__(self, obj) -> None:
        obj.__dict__.pop(self._name, None)


# -- lock construction wrapping (held-set tracking) --------------------------


class LocksetLock:
    """Held-set-tracking proxy around whatever lock the current factory
    produces (raw, witness-wrapped, explorer-wrapped — composes)."""

    def __init__(self, inner):
        self._psr_inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._psr_inner.acquire(blocking, timeout)
        if got:
            _tls.held.append(id(self))
        return got

    def release(self) -> None:
        self._psr_inner.release()
        try:
            _tls.held.remove(id(self))
        except ValueError:
            pass

    def __enter__(self) -> "LocksetLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self):
        return self._psr_inner.locked()

    def __getattr__(self, name: str):
        return getattr(self._psr_inner, name)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<LocksetLock of {self._psr_inner!r}>"


def _package_site() -> bool:
    f = sys._getframe(2)
    fn = f.f_code.co_filename
    i = fn.rfind(_PKG_MARKER)
    if i < 0:
        return False
    rel = fn[i + len(_PKG_MARKER):].replace(os.sep, "/")
    return not rel.startswith("analysis/")


def _lock_factory():
    inner = _orig["Lock"]()
    return LocksetLock(inner) if _package_site() else inner


def _rlock_factory():
    inner = _orig["RLock"]()
    return LocksetLock(inner) if _package_site() else inner


def _cond_factory(lock=None):
    if lock is None and _package_site():
        lock = LocksetLock(_orig["RLock"]())
    if lock is not None:
        return _orig["Condition"](lock)
    return _orig["Condition"]()


# -- public surface ----------------------------------------------------------


def wrap(inner) -> LocksetLock:
    """Explicitly wrap a raw lock (tests; ad-hoc instrumentation of a
    lock constructed outside package modules)."""
    return LocksetLock(inner)


def install() -> None:
    """Arm process-wide (idempotent, reference-counted, composes over
    the witness/explorer factories). Arm BEFORE constructing the
    objects to observe — their locks must be wrapped and their
    registration hooks must see the armed state."""
    global _installs
    _installs += 1
    if _installs > 1:
        return
    _orig["Lock"] = threading.Lock
    _orig["RLock"] = threading.RLock
    _orig["Condition"] = threading.Condition
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _cond_factory


def uninstall() -> None:
    global _installs
    if _installs == 0:
        return
    _installs -= 1
    if _installs > 0:
        return
    threading.Lock = _orig["Lock"]
    threading.RLock = _orig["RLock"]
    threading.Condition = _orig["Condition"]
    with _lock:
        for cls, fields_ in _instrumented.items():
            for fname in fields_:
                desc = cls.__dict__.get(fname)
                if isinstance(desc, _RaceField):
                    if desc._prev is not None:
                        setattr(cls, fname, desc._prev)
                    else:
                        delattr(cls, fname)
        _instrumented.clear()
        _fields.clear()


def installed() -> bool:
    return _installs > 0


def track(obj, fields_: tuple[str, ...], name: str = "") -> None:
    """Register one shared object's fields for lockset checking. No-op
    while disarmed — the registration hooks in the owning constructors
    stay free in production."""
    if _installs == 0:
        return
    cls = type(obj)
    label = name or f"{cls.__name__}#{id(obj) & 0xFFFF:04x}"
    with _lock:
        done = _instrumented.setdefault(cls, set())
        for fname in fields_:
            if fname not in done:
                prev = cls.__dict__.get(fname)
                # migrate any value assigned before instrumentation
                # into the instance dict the descriptor reads
                setattr(cls, fname, _RaceField(fname, prev))
                done.add(fname)
            _fields[(id(obj), fname)] = _FieldState(name=label)
        obj.__dict__[_MARKER] = label


def reports() -> list[RaceReport]:
    with _lock:
        return list(_reports)


def clear() -> None:
    with _lock:
        _reports.clear()
        _fields.clear()


def assert_no_races() -> None:
    """The acceptance form: raise (rendering every report) if the armed
    run witnessed any unlocked conflicting pair."""
    reps = reports()
    if reps:
        raise AssertionError(
            f"{len(reps)} data race(s) witnessed:\n\n"
            + "\n\n".join(r.render() for r in reps)
        )


def maybe_install_from_env() -> bool:
    """Chaos-style opt-in: ``PS_RACE_WITNESS=1`` arms at package import
    (parallel/__init__), like the lock witness and the explorer."""
    if os.environ.get(ENV_VAR, "") not in ("", "0"):
        install()
        return True
    return False
