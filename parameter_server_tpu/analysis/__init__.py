"""pslint — project-native static analysis for parameter_server_tpu.

``python -m parameter_server_tpu.analysis`` (or ``cli lint``) walks the
package and fails on violations of the concurrency and contract
invariants PRs 1-4 introduced:

    lock-order           static lock-acquisition graph must be acyclic
    blocking-under-lock  no socket/send/recv, sleep, Future.result,
                         RPC call, or jit/device sync while holding a lock
    settle-exactly-once  every DeferredReply is returned and settled on
                         all exit paths, exception edges included
    counter-contract     every bumped counter renders in cli stats
    config-contract      every cfg.<section>.<key> read has a default
    replycache-contract  reply-cache exemption sets (idempotent/blocking/
                         prio cmds) name only served commands, and every
                         served command has a binary cmd id
    trace-hygiene        spans only via `with trace.span(...)` / @traced
    pragma-hygiene       every suppression carries a justification
    rcu                  published (state, version) snapshots are never
                         mutated; raw publish-attr access stays under
                         the apply lock or the snapshot property
                         (dataflow-backed: analysis/dataflow.py)
    wireproto            binary-header slot tables encode<->decode in
                         lockstep with v2 version gating, _CMD_IDS stays
                         collision-free, feature adverts have both
                         sides, every queued reply flows through
                         decorated() (dataflow-backed)
    stale-pragma         a justified pragma that suppresses nothing is
                         itself a finding (suppressions can't outlive
                         the code they excused)
    spec-conformance     the psmc protocol models' declared ASSUMPTIONS
                         (analysis/specs/) match the AST-derived code
                         tables — model and code cannot drift silently
                         (analysis/conformance.py)
    model-invariants     the tier-1-bounded model suite itself verifies
                         clean (exactly-once / rcu / ssp / failover)
    flightrec-contract   every flightrec.record() event is known to the
                         postmortem plane, and every stitched/flagged
                         event name is actually emitted
    units                dimension lattice (us/ms/s/bytes/count/clocks)
                         inferred from name suffixes + literal factor
                         conversions; cross-unit arithmetic/comparison
                         and unit-mismatched sinks are findings
                         (dataflow-backed: analysis/quantity.py)
    clockdomain          timestamps tagged by source clock (wall/mono/
                         perf_counter/peer-echoed foreign-wall); mixing
                         domains in -, <, min/max outside a declared
                         skew clamp is a finding (dataflow-backed)
    idtype               opaque identities (cid/seq/rank/ver/key/trace)
                         are their own types: cross-space comparison,
                         arithmetic on opaque ids (ver equality-only),
                         and call-boundary id swaps are findings
                         (dataflow-backed)

Suppressions: ``# psl: ignore[<checker>]: <why>`` at the flagged line;
tree policy in pyproject.toml ``[tool.pslint]``. The runtime complements:
analysis/witness.py (``PS_LOCK_WITNESS=1``) enforces lock order on the
orders a live process ACTUALLY takes, and analysis/explorer.py
(``PS_SCHED=<seed>``) forces seeded adversarial interleavings at
lock/queue/RCU-publish boundaries and replays them from the seed.

Adding a checker: one module exporting ``check_<name>(index)``, one line
in ``CHECKERS`` below, one positive+negative test in tests/test_pslint.py.
"""

from __future__ import annotations

from pathlib import Path

from parameter_server_tpu.analysis.blocking import check_blocking_under_lock
from parameter_server_tpu.analysis.conformance import (
    check_model_invariants,
    check_spec_conformance,
    derive_code_tables,
)
from parameter_server_tpu.analysis.contracts import (
    check_config_contract,
    check_counter_contract,
    config_key_usage,
    counter_inventory,
)
from parameter_server_tpu.analysis.core import (
    PACKAGE_ROOT,
    Checker,
    Finding,
    PackageIndex,
    PslintConfig,
    check_pragma_hygiene,
    check_stale_pragma,
    load_package,
    run_checkers,
)
from parameter_server_tpu.analysis.flightreccontract import (
    check_flightrec_contract,
)
from parameter_server_tpu.analysis.lockgraph import (
    build_lock_graph,
    check_lock_order,
)
from parameter_server_tpu.analysis.quantity import (
    check_clockdomain,
    check_idtype,
    check_units,
)
from parameter_server_tpu.analysis.rcu import check_rcu
from parameter_server_tpu.analysis.replycache import check_replycache_contract
from parameter_server_tpu.analysis.settle import check_settle_exactly_once
from parameter_server_tpu.analysis.tracehygiene import check_trace_hygiene
from parameter_server_tpu.analysis.wireproto import check_wireproto

__all__ = [
    "CHECKERS",
    "Checker",
    "Finding",
    "PackageIndex",
    "PslintConfig",
    "SEVERITY_WARN_DEFAULT",
    "analyze_package",
    "analyze_sources",
    "build_lock_graph",
    "config_key_usage",
    "counter_inventory",
    "derive_code_tables",
    "load_package",
    "severity_of",
]

#: name -> checker; the registry every later PR extends
CHECKERS: dict[str, Checker] = {
    "lock-order": check_lock_order,
    "blocking-under-lock": check_blocking_under_lock,
    "settle-exactly-once": check_settle_exactly_once,
    "counter-contract": check_counter_contract,
    "config-contract": check_config_contract,
    "replycache-contract": check_replycache_contract,
    "trace-hygiene": check_trace_hygiene,
    "pragma-hygiene": check_pragma_hygiene,
    # ISSUE 8 (pslint v2): the dataflow-backed pair + the pragma audit
    "rcu": check_rcu,
    "wireproto": check_wireproto,
    # special-cased by run_checkers: audits suppression USAGE, so it
    # runs off the other enabled checkers' raw findings
    "stale-pragma": check_stale_pragma,
    # ISSUE 10 (psmc): spec<->code conformance + the bounded model
    # suite, and the flightrec/postmortem event-table contract
    "spec-conformance": check_spec_conformance,
    "model-invariants": check_model_invariants,
    "flightrec-contract": check_flightrec_contract,
    # ISSUE 20 (pslint v3): quantity-flow triple over the shared
    # dataflow fixpoint (analysis/flowrun.py)
    "units": check_units,
    "clockdomain": check_clockdomain,
    "idtype": check_idtype,
}

#: checkers whose findings default to "warn" severity (exit 2, not 1)
#: when nothing in ``[tool.pslint] warn`` says otherwise; everything
#: else is "error". Severity tiers exist so CI can gate hard on errors
#: while new analyses phase in as warnings.
SEVERITY_WARN_DEFAULT: frozenset[str] = frozenset()


def severity_of(checker: str, config: PslintConfig | None = None) -> str:
    """"error" or "warn" for one checker, honoring ``[tool.pslint]
    warn`` (the config list EXTENDS the built-in default set)."""
    warn = set(SEVERITY_WARN_DEFAULT)
    if config is not None:
        warn |= set(config.warn)
    return "warn" if checker in warn else "error"


def _default_config(root: Path) -> PslintConfig:
    # [tool.pslint] lives in the repo's pyproject.toml, one level above
    # the package dir
    return PslintConfig.load(root.parent / "pyproject.toml")


def analyze_package(
    root: Path | str = PACKAGE_ROOT,
    checkers: dict[str, Checker] | None = None,
    config: PslintConfig | None = None,
) -> list[Finding]:
    """Run the full analyzer over the real package; empty == clean."""
    root = Path(root)
    config = config if config is not None else _default_config(root)
    index = load_package(root, config)
    return run_checkers(index, checkers or CHECKERS, config)


def analyze_sources(
    sources: dict[str, str],
    checkers: dict[str, Checker] | None = None,
) -> list[Finding]:
    """Run checkers over in-memory sources (tests: crafted snippets)."""
    index = PackageIndex.from_sources(sources)
    return run_checkers(index, checkers or CHECKERS, PslintConfig())
