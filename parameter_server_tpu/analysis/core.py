"""pslint core: package loading, findings, suppressions, the runner.

The analyzer is project-native: checkers encode THIS codebase's
concurrency and contract invariants (lock ordering, no blocking calls
under a lock, DeferredReply settlement, counter/config inventories,
trace span hygiene) instead of generic style rules. Each checker is a
function ``(PackageIndex) -> list[Finding]`` registered in
``analysis/__init__.py``; adding a checker to a later PR is one module
plus one registry line.

Suppressions are explicit and audited:

- file:line pragma — ``# psl: ignore[<checker>]: <justification>`` on
  the flagged line (or a standalone comment on the line directly
  above). The justification string is REQUIRED; a bare pragma is itself
  a finding (``pragma-hygiene``), so every silenced warning carries its
  reason in the diff forever.
- ``[tool.pslint]`` in pyproject.toml — ``exclude`` path globs and
  ``disable`` checker names for whole-tree policy.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

#: package root of the code under analysis (the installed package dir)
PACKAGE_ROOT = Path(__file__).resolve().parent.parent

_PRAGMA_RE = re.compile(
    r"#\s*psl:\s*ignore\[([a-z0-9_*,\s-]+)\]\s*(?::\s*(.*\S))?\s*$"
)


@dataclass(frozen=True)
class Finding:
    """One analyzer violation, pointing at a repo file:line."""

    checker: str
    path: str  # relative to the analyzed root
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"


@dataclass
class Pragma:
    line: int  # the line the pragma SUPPRESSES (not where it sits)
    checkers: frozenset[str]  # {"*"} suppresses every checker
    justification: str
    pragma_line: int  # where the comment physically lives


@dataclass
class SourceFile:
    """One parsed module: AST + raw text + its suppression pragmas."""

    path: Path
    relpath: str
    text: str
    tree: ast.Module
    pragmas: dict[int, Pragma] = field(default_factory=dict)

    def line(self, lineno: int) -> str:
        lines = self.text.splitlines()
        return lines[lineno - 1] if 0 < lineno <= len(lines) else ""


@dataclass
class PslintConfig:
    """``[tool.pslint]`` policy (pyproject.toml)."""

    exclude: list[str] = field(default_factory=list)  # relpath globs
    disable: list[str] = field(default_factory=list)  # checker names
    #: checkers demoted to "warn" severity (tiered exit codes: errors
    #: exit 1, warn-only runs exit 2) — how a new analysis phases in
    #: without invalidating an error-gating CI baseline workflow
    warn: list[str] = field(default_factory=list)
    #: quantity-checker whitelists (ISSUE 20). Grammar (each entry one
    #: string, whitespace-tolerant):
    #:   unit-conversions:  "<fn_name> -> <unit>"  — a call to this
    #:       function returns a value of <unit> (us|ms|s|bytes|count|
    #:       clocks) whatever its arguments carry; this is how a
    #:       blessed conversion crosses the dimension lattice without a
    #:       finding (name-suffix inference covers helpers like
    #:       ``now_wall_us`` already — list only the exceptions).
    unit_conversions: list[str] = field(default_factory=list)
    #:   clock-clamps: "<fn_name>" — a declared skew boundary: clock-
    #:       domain mixing inside this function's body or anywhere in
    #:       its call arguments is sanctioned (extends the built-in
    #:       convention that any function whose name contains "clamp"
    #:       is a skew boundary).
    clock_clamps: list[str] = field(default_factory=list)
    #:   clock-foreign-keys: "<header_key>" — a wire/header field whose
    #:       value is a PEER's wall-clock timestamp (foreign-wall
    #:       domain; extends the built-in {"pts"}).
    clock_foreign_keys: list[str] = field(default_factory=list)

    @classmethod
    def load(cls, pyproject: Path | None) -> "PslintConfig":
        if pyproject is None or not pyproject.exists():
            return cls()
        from parameter_server_tpu.utils.config import toml_module

        data = toml_module().loads(pyproject.read_text())
        sec = data.get("tool", {}).get("pslint", {})
        return cls(
            exclude=list(sec.get("exclude", [])),
            disable=list(sec.get("disable", [])),
            warn=list(sec.get("warn", [])),
            unit_conversions=list(sec.get("unit-conversions", [])),
            clock_clamps=list(sec.get("clock-clamps", [])),
            clock_foreign_keys=list(sec.get("clock-foreign-keys", [])),
        )


def _parse_pragmas(text: str) -> dict[int, Pragma]:
    """Map suppressed-line -> Pragma. A pragma trailing code suppresses
    its own line; a pragma on a comment-only line suppresses the NEXT
    line (for statements too long to share a line with their reason).

    Parsed from COMMENT tokens, not raw lines: a pragma-shaped string
    inside a docstring (this package documents its own grammar) is
    prose, not a suppression — the line-regex form silently treated it
    as one, which both confused the stale-pragma audit and could have
    let a docstring suppress a real finding on its own line."""
    import io
    import tokenize

    out: dict[int, Pragma] = {}
    lines = text.splitlines()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(text).readline))
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        tokens = []
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _PRAGMA_RE.search(tok.string)
        if m is None:
            continue
        i = tok.start[0]
        checkers = frozenset(
            c.strip() for c in m.group(1).split(",") if c.strip()
        )
        raw = lines[i - 1] if 0 < i <= len(lines) else ""
        target = i + 1 if raw.lstrip().startswith("#") else i
        out[target] = Pragma(
            line=target,
            checkers=checkers,
            justification=(m.group(2) or "").strip(),
            pragma_line=i,
        )
    return out


class PackageIndex:
    """Parsed view of every analyzed module, shared by all checkers.

    Every file is read and ``ast.parse``d exactly once, here — checkers
    only ever walk the trees this index already holds. The index also
    carries the :class:`PslintConfig` it was loaded under, because
    checkers receive nothing but the index and the quantity checkers
    (units/clockdomain/idtype) need the whitelist grammar from
    ``[tool.pslint]``.
    """

    def __init__(
        self,
        files: list[SourceFile],
        root: Path,
        config: PslintConfig | None = None,
    ):
        self.files = files
        self.root = root
        self.config = config or PslintConfig()
        self._by_rel = {f.relpath: f for f in files}

    def get(self, relpath: str) -> SourceFile | None:
        return self._by_rel.get(relpath)

    @classmethod
    def from_sources(
        cls,
        sources: dict[str, str],
        root: Path | None = None,
        config: PslintConfig | None = None,
    ) -> "PackageIndex":
        """In-memory index (tests: crafted positive/negative snippets)."""
        files = [
            SourceFile(
                path=Path(rel),
                relpath=rel,
                text=src,
                tree=ast.parse(src, filename=rel),
                pragmas=_parse_pragmas(src),
            )
            for rel, src in sources.items()
        ]
        return cls(files, root or Path("."), config)


def load_package(
    root: Path | str = PACKAGE_ROOT, config: PslintConfig | None = None
) -> PackageIndex:
    root = Path(root)
    config = config or PslintConfig()
    files: list[SourceFile] = []
    for p in sorted(root.rglob("*.py")):
        rel = p.relative_to(root).as_posix()
        if any(fnmatch.fnmatch(rel, g) for g in config.exclude):
            continue
        text = p.read_text()
        files.append(
            SourceFile(
                path=p,
                relpath=rel,
                text=text,
                tree=ast.parse(text, filename=str(p)),
                pragmas=_parse_pragmas(text),
            )
        )
    return PackageIndex(files, root, config)


Checker = Callable[[PackageIndex], list[Finding]]


def check_pragma_hygiene(index: PackageIndex) -> list[Finding]:
    """A suppression without a justification is a violation: the pragma
    grammar REQUIRES ``# psl: ignore[checker]: why`` so silenced
    findings stay auditable in the diff."""
    out: list[Finding] = []
    for f in index.files:
        for pr in f.pragmas.values():
            if not pr.justification:
                out.append(Finding(
                    "pragma-hygiene", f.relpath, pr.pragma_line,
                    "pslint pragma without a justification string "
                    "(required form: # psl: ignore[<checker>]: <why>)",
                ))
            if not pr.checkers:
                out.append(Finding(
                    "pragma-hygiene", f.relpath, pr.pragma_line,
                    "pslint pragma names no checker",
                ))
    return out


def apply_suppressions(
    index: PackageIndex,
    findings: list[Finding],
    used: set[tuple[str, int]] | None = None,
) -> list[Finding]:
    """Drop pragma-suppressed findings. ``used`` (when given) collects
    the ``(relpath, pragma_line)`` of every pragma that actually
    suppressed something — the stale-pragma audit's input."""
    out = []
    for fi in findings:
        sf = index.get(fi.path)
        if sf is not None and fi.checker != "pragma-hygiene":
            pr = sf.pragmas.get(fi.line)
            if pr is not None and pr.justification and (
                "*" in pr.checkers or fi.checker in pr.checkers
            ):
                if used is not None:
                    used.add((fi.path, pr.pragma_line))
                continue
        out.append(fi)
    return out


def check_stale_pragma(index: PackageIndex) -> list[Finding]:
    """Registry placeholder: the audit needs every OTHER enabled
    checker's raw findings, so :func:`run_checkers` drives it (see
    ``stale_pragma_findings``). Running it standalone is vacuous."""
    return []


def stale_pragma_findings(
    index: PackageIndex,
    used: set[tuple[str, int]],
    enabled: set[str],
    full_registry: set[str],
) -> list[Finding]:
    """A justified pragma that no longer suppresses any finding is
    itself a finding: the code it excused was fixed or deleted, and a
    suppression that outlives its reason is a hole the next real
    violation walks through unnoticed. Audited conservatively: a pragma
    is only judged when every checker it names actually ran (``*``
    pragmas only under the full registry), so ``--checker`` subset runs
    can never flag a pragma whose checker they skipped. A checker name
    outside the registry is flagged unconditionally — a typo'd pragma
    never suppressed anything to begin with."""
    out: list[Finding] = []
    for f in index.files:
        for pr in f.pragmas.values():
            if not pr.justification or not pr.checkers:
                continue  # pragma-hygiene's findings, not stale ones
            if (f.relpath, pr.pragma_line) in used:
                continue
            unknown = sorted(
                c for c in pr.checkers
                if c != "*" and c not in full_registry
            )
            if unknown:
                out.append(Finding(
                    "stale-pragma", f.relpath, pr.pragma_line,
                    f"pragma names unknown checker(s) {unknown} — it "
                    "has never suppressed anything (typo?); known: "
                    + ", ".join(sorted(full_registry)),
                ))
                continue
            names = (
                full_registry if "*" in pr.checkers else set(pr.checkers)
            )
            if not names <= enabled:
                continue  # a named checker didn't run: can't judge
            out.append(Finding(
                "stale-pragma", f.relpath, pr.pragma_line,
                "stale pragma: # psl: ignore["
                + ",".join(sorted(pr.checkers))
                + "] suppresses no finding on its line — the code it "
                "excused is gone; delete the pragma so the suppression "
                "can't outlive its reason",
            ))
    return out


def run_checkers(
    index: PackageIndex,
    checkers: dict[str, Checker],
    config: PslintConfig | None = None,
) -> list[Finding]:
    """Run every enabled checker and apply pragma suppressions; the
    returned list is what gates CI (empty == clean). The stale-pragma
    audit runs last, over the suppression usage this run observed."""
    config = config or PslintConfig()
    findings: list[Finding] = []
    enabled: set[str] = set()
    for name, fn in checkers.items():
        if name in config.disable:
            continue
        enabled.add(name)
        if name == "stale-pragma":
            continue  # driven below, off the other checkers' output
        findings.extend(fn(index))
    used: set[tuple[str, int]] = set()
    findings = apply_suppressions(index, findings, used)
    if "stale-pragma" in enabled:
        from parameter_server_tpu.analysis import CHECKERS

        stale = stale_pragma_findings(
            index, used, enabled, set(CHECKERS)
        )
        # stale findings are suppressible, but ONLY by a pragma naming
        # stale-pragma EXPLICITLY (a pragma kept deliberately for a
        # flapping platform-dependent finding says why with its own
        # justification). A wildcard must not count: an unused
        # `ignore[*]` would otherwise suppress its own staleness — the
        # broadest suppression becoming the one the audit can't retire.
        for fi in stale:
            sf = index.get(fi.path)
            pr = sf.pragmas.get(fi.line) if sf is not None else None
            if (
                pr is not None
                and pr.justification
                and "stale-pragma" in pr.checkers
            ):
                continue
            findings.append(fi)
    findings.sort(key=lambda fi: (fi.path, fi.line, fi.checker))
    return findings


# ---------------------------------------------------------------------------
# shared AST utilities used by the concurrency checkers
# ---------------------------------------------------------------------------


def unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - exotic nodes
        return "<expr>"


_LOCK_CTORS = {"Lock", "RLock", "Condition"}


def lock_ctor_name(call: ast.AST) -> str | None:
    """``threading.Lock()`` / ``Lock()`` -> "Lock" (None otherwise)."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in _LOCK_CTORS:
        if isinstance(fn.value, ast.Name) and fn.value.id == "threading":
            return fn.attr
    if isinstance(fn, ast.Name) and fn.id in _LOCK_CTORS:
        return fn.id
    return None


def iter_functions(
    tree: ast.Module,
) -> list[tuple[str | None, ast.FunctionDef | ast.AsyncFunctionDef]]:
    """Every (owning class name or None, function) in a module. Nested
    functions are yielded under their enclosing class (closures over
    ``self`` — the server loop's helpers — analyze with class context)."""
    out: list[tuple[str | None, Any]] = []

    def walk(node: ast.AST, cls: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((cls, child))
                walk(child, cls)
            else:
                walk(child, cls)

    walk(tree, None)
    return out


class HeldLockWalker:
    """Statement-order walk of one function body tracking which lock
    expressions are held (``with`` statements over lock-typed
    expressions). Subclasses get ``on_call(node, held)`` for every Call
    observed with the current held stack (list of (lock_key, expr_str,
    with_line))."""

    def __init__(self, is_lock_expr: Callable[[ast.AST], str | None]):
        # is_lock_expr: context expr -> lock key (None: not a lock)
        self._is_lock = is_lock_expr

    def on_call(
        self, node: ast.Call, held: list[tuple[str, str, int]]
    ) -> None:  # pragma: no cover - overridden
        raise NotImplementedError

    def on_acquire(
        self, key: str, held: list[tuple[str, str, int]], line: int
    ) -> None:
        """Called when a ``with <lock>`` is entered, BEFORE the lock is
        pushed onto ``held`` (the lock-order checker's edge source)."""

    def walk_function(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> None:
        self._walk_body(fn.body, [])

    def _walk_body(self, body: list, held: list[tuple[str, str, int]]) -> None:
        for stmt in body:
            self._walk_stmt(stmt, held)

    def _walk_stmt(self, stmt: ast.stmt, held: list) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs run later, not while these locks are held
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in stmt.items:
                self._visit_expr(item.context_expr, held)
                key = self._is_lock(item.context_expr)
                if key is not None:
                    self.on_acquire(key, held, stmt.lineno)
                    held.append(
                        (key, unparse(item.context_expr), stmt.lineno)
                    )
                    pushed += 1
            self._walk_body(stmt.body, held)
            for _ in range(pushed):
                held.pop()
            return
        if isinstance(stmt, ast.Try):
            self._walk_body(stmt.body, held)
            for h in stmt.handlers:
                self._walk_body(h.body, held)
            self._walk_body(stmt.orelse, held)
            self._walk_body(stmt.finalbody, held)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._visit_expr(stmt.test, held)
            self._walk_body(stmt.body, held)
            self._walk_body(stmt.orelse, held)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._visit_expr(stmt.iter, held)
            self._walk_body(stmt.body, held)
            self._walk_body(stmt.orelse, held)
            return
        # expression-bearing simple statements
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call):
                self.on_call(sub, held)

    def _visit_expr(self, expr: ast.AST, held: list) -> None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                self.on_call(sub, held)
