"""Checker ``lock-order``: static lock-acquisition graph, cycles rejected.

Every ``with <lock>`` nest (directly, or through a call to a method /
known singleton / constructor whose transitive summary acquires a lock)
contributes a directed edge *held -> acquired*. A cycle in that graph is
a potential deadlock — two threads walking the cycle from different
entry points park on each other forever — and fails the build.

Lock identity is the DEFINING class attribute (``RpcClient._cv``,
``ShardServer._lock``) or ``<relpath>:<name>`` for module-level locks,
so the same discipline is enforced across files. The derived graph (and
each lock's construction sites) also feeds the runtime witness
(analysis/witness.py): an execution that acquires locks against a
statically-known edge raises immediately, with the offending pair named.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from parameter_server_tpu.analysis.callgraph import (
    CallGraph,
    OwnerKey,
    shared_callgraph,
)
from parameter_server_tpu.analysis.core import (
    Finding,
    HeldLockWalker,
    PackageIndex,
    iter_functions,
)


@dataclass
class LockGraph:
    #: (held_key, acquired_key) -> first site witnessing the edge
    edges: dict[tuple[str, str], tuple[str, int]] = field(default_factory=dict)
    #: lock key -> [(relpath, construction line)]
    sites: dict[str, list[tuple[str, int]]] = field(default_factory=dict)

    def add(self, a: str, b: str, site: tuple[str, int]) -> None:
        if a != b:  # same-key nesting is re-entrancy, not ordering
            self.edges.setdefault((a, b), site)

    def cycles(self) -> list[tuple[list[str], tuple[str, int]]]:
        """Every distinct cycle (as a key path a -> ... -> a), with the
        site of the edge closing it."""
        adj: dict[str, list[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        out: list[tuple[list[str], tuple[str, int]]] = []
        seen_cycles: set[frozenset[str]] = set()

        def dfs(start: str, node: str, path: list[str], on_path: set[str]) -> None:
            for nxt in adj.get(node, ()):  # noqa: B007
                if nxt == start:
                    key = frozenset(path)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append(
                            (path + [start], self.edges[(node, start)])
                        )
                elif nxt not in on_path and nxt > start:
                    # only walk keys ordered after the start: each cycle
                    # is found once, from its smallest key
                    dfs(start, nxt, path + [nxt], on_path | {nxt})

        for start in sorted(adj):
            dfs(start, start, [start], {start})
        return out


class _EdgeWalker(HeldLockWalker):
    def __init__(
        self,
        graph: CallGraph,
        out: LockGraph,
        relpath: str,
        cls_name: str | None,
        summaries: dict[OwnerKey, frozenset[str]],
    ):
        super().__init__(self._lock_key)
        self._graph = graph
        self._out = out
        self._relpath = relpath
        self._cls = cls_name
        self._summaries = summaries

    def _lock_key(self, expr: ast.AST) -> str | None:
        g = self._graph
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and self._cls is not None
        ):
            return g.lock_attr_key(self._cls, expr.attr)
        if isinstance(expr, ast.Name):
            return g.module_locks.get(expr.id)
        return None

    def on_acquire(self, key: str, held: list, line: int) -> None:
        for h, _, _ in held:
            self._out.add(h, key, (self._relpath, line))

    def on_call(self, node: ast.Call, held: list) -> None:
        if not held:
            return
        acquired: set[str] = set()
        for callee in self._graph.callees(self._relpath, self._cls, node):
            acquired |= self._summaries.get(callee, frozenset())
        for key in acquired:
            for h, _, _ in held:
                self._out.add(h, key, (self._relpath, node.lineno))


def _direct_locks(
    graph: CallGraph,
) -> "dict[OwnerKey, frozenset[str]]":
    """Transitive may-acquire summary per function."""

    def direct(owner: OwnerKey, relpath: str, cls_name, fndef) -> frozenset[str]:
        keys: set[str] = set()

        class _Collect(HeldLockWalker):
            def __init__(self) -> None:
                super().__init__(self._lock_key)

            def _lock_key(self, expr: ast.AST) -> str | None:
                if (
                    isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"
                    and cls_name is not None
                ):
                    return graph.lock_attr_key(cls_name, expr.attr)
                if isinstance(expr, ast.Name):
                    return graph.module_locks.get(expr.id)
                return None

            def on_acquire(self, key: str, held: list, line: int) -> None:
                keys.add(key)

            def on_call(self, node: ast.Call, held: list) -> None:
                pass

        _Collect().walk_function(fndef)
        return frozenset(keys)

    return graph.summarize(
        direct=direct,
        merge=lambda a, b: a | b,
        bottom=frozenset,
    )


def build_lock_graph(
    index: PackageIndex, graph: CallGraph | None = None
) -> LockGraph:
    graph = graph or shared_callgraph(index)
    out = LockGraph(sites=graph.all_lock_keys())
    summaries = _direct_locks(graph)
    for f in index.files:
        for cls_name, fndef in iter_functions(f.tree):
            _EdgeWalker(graph, out, f.relpath, cls_name, summaries).walk_function(
                fndef
            )
    return out


def check_lock_order(index: PackageIndex) -> list[Finding]:
    lg = build_lock_graph(index)
    out: list[Finding] = []
    for path, site in lg.cycles():
        rel, line = site
        out.append(Finding(
            "lock-order", rel, line,
            "lock acquisition cycle: " + " -> ".join(path)
            + " (two threads entering this cycle at different points "
            "deadlock); break the cycle or invert one nesting",
        ))
    return out
