"""Lightweight package call graph for the concurrency checkers.

The lock-order and blocking-under-lock checkers both need the same two
facts about a call made while a lock is held: *which function does this
resolve to* and *what does that function do transitively*. This module
builds the resolution tables once per analysis run:

- every class (methods, base names, ``self.X = threading.Lock()`` lock
  attributes with construction sites, ``self.Y = SomeClass(...)``
  attribute types),
- every module-level function,
- module-level instances (``wire_counters = CounterSet()``) and
  module-level locks, visible across files through import aliasing,

and offers ``callees()`` (syntactic call -> owner keys) plus a generic
``summarize()`` fixpoint so a checker can fold any per-function fact
(locks acquired, may-block) transitively through self-calls, attribute
calls, known-instance calls and constructors. Deliberately
intraprocedural-plus-one-table: no type inference, no dynamic dispatch —
precise enough for this package's idioms, simple enough to audit.
"""

from __future__ import annotations

import ast
import weakref
from dataclasses import dataclass, field
from typing import Any, Callable

from parameter_server_tpu.analysis.core import PackageIndex, lock_ctor_name

#: owner key of a function body: ("m", class_name, method_name) or
#: ("f", relpath, func_name)
OwnerKey = tuple[str, str, str]

_shared: "weakref.WeakKeyDictionary[PackageIndex, CallGraph]" = (
    weakref.WeakKeyDictionary()
)


def shared_callgraph(index: PackageIndex) -> "CallGraph":
    """One CallGraph per index, shared by every checker in a run: the
    tables are build-once read-only, and with three dataflow-backed
    checkers plus the lock pair all resolving calls, rebuilding per
    checker would walk the whole package's ASTs five times per lint."""
    g = _shared.get(index)
    if g is None:
        g = _shared[index] = CallGraph(index)
    return g


@dataclass
class ClassInfo:
    name: str
    relpath: str
    bases: list[str] = field(default_factory=list)
    methods: dict[str, ast.FunctionDef] = field(default_factory=dict)
    #: lock attr -> [(relpath, ctor line)] (several on rebind)
    lock_attrs: dict[str, list[tuple[str, int]]] = field(default_factory=dict)
    #: attr -> package class name it is assigned an instance of
    attr_types: dict[str, str] = field(default_factory=dict)


class CallGraph:
    def __init__(self, index: PackageIndex):
        self.index = index
        self.classes: dict[str, ClassInfo] = {}
        self.mod_funcs: dict[tuple[str, str], ast.FunctionDef] = {}
        self._funcs_by_name: dict[str, list[tuple[str, ast.FunctionDef]]] = {}
        #: instance name -> class name (module-level singletons)
        self.global_instances: dict[str, str] = {}
        #: module-level lock name -> lock key
        self.module_locks: dict[str, str] = {}
        self.module_lock_sites: dict[str, list[tuple[str, int]]] = {}
        #: relpath -> {local name -> module relpath} (module aliases)
        self.module_aliases: dict[str, dict[str, str]] = {}
        self._collect()

    # -- pass 1: tables ---------------------------------------------------

    def _collect(self) -> None:
        for f in self.index.files:
            self.module_aliases[f.relpath] = {}
            for node in f.tree.body:
                self._collect_top(f.relpath, node)
        # second sweep: module instances may refer to classes defined in
        # other files (imported names) — resolve after all classes known
        for f in self.index.files:
            for node in f.tree.body:
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    fn = node.value.func
                    cls = (
                        fn.id
                        if isinstance(fn, ast.Name) and fn.id in self.classes
                        else None
                    )
                    if cls:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                self.global_instances[t.id] = cls

    def _collect_top(self, relpath: str, node: ast.stmt) -> None:
        if isinstance(node, ast.ClassDef):
            self._collect_class(relpath, node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.mod_funcs[(relpath, node.name)] = node
            self._funcs_by_name.setdefault(node.name, []).append(
                (relpath, node)
            )
        elif isinstance(node, ast.Assign):
            kind = lock_ctor_name(node.value)
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                if kind is not None:
                    key = f"{relpath}:{t.id}"
                    self.module_locks[t.id] = key
                    self.module_lock_sites.setdefault(key, []).append(
                        (relpath, node.value.lineno)
                    )
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            self._collect_import(relpath, node)

    def _collect_import(self, relpath: str, node: ast.stmt) -> None:
        # map "from parameter_server_tpu.kv import store as kv_store" and
        # "from parameter_server_tpu.utils import trace" to module
        # relpaths so `kv_store.push(...)` resolves to a function body
        pkg = "parameter_server_tpu"
        if isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                dotted = f"{node.module}.{a.name}"
                rel = self._module_rel(dotted, pkg)
                if rel is not None:
                    self.module_aliases[relpath][a.asname or a.name] = rel
        elif isinstance(node, ast.Import):
            for a in node.names:
                rel = self._module_rel(a.name, pkg)
                if rel is not None:
                    self.module_aliases[relpath][
                        a.asname or a.name.split(".")[-1]
                    ] = rel

    def _module_rel(self, dotted: str, pkg: str) -> str | None:
        if not dotted.startswith(pkg + "."):
            return None
        rel = dotted[len(pkg) + 1 :].replace(".", "/") + ".py"
        return rel if self.index.get(rel) is not None else None

    def _collect_class(self, relpath: str, node: ast.ClassDef) -> None:
        info = ClassInfo(
            name=node.name,
            relpath=relpath,
            bases=[b.id for b in node.bases if isinstance(b, ast.Name)],
        )
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods[item.name] = item
        # self.X = threading.Lock() / self.Y = SomeClass(...) anywhere in
        # the class body (constructed outside __init__ too)
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign):
                continue
            for t in sub.targets:
                if not (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    continue
                if lock_ctor_name(sub.value) is not None:
                    info.lock_attrs.setdefault(t.attr, []).append(
                        (relpath, sub.value.lineno)
                    )
                elif isinstance(sub.value, ast.Call) and isinstance(
                    sub.value.func, ast.Name
                ):
                    info.attr_types[t.attr] = sub.value.func.id
        self.classes[node.name] = info

    # -- resolution -------------------------------------------------------

    def mro(self, cls_name: str) -> list[ClassInfo]:
        out: list[ClassInfo] = []
        seen: set[str] = set()
        stack = [cls_name]
        while stack:
            n = stack.pop(0)
            if n in seen or n not in self.classes:
                continue
            seen.add(n)
            info = self.classes[n]
            out.append(info)
            stack.extend(info.bases)
        return out

    def resolve_method(self, cls_name: str, mname: str) -> OwnerKey | None:
        for info in self.mro(cls_name):
            if mname in info.methods:
                return ("m", info.name, mname)
        return None

    def lock_attr_key(self, cls_name: str, attr: str) -> str | None:
        """``self.<attr>`` in class ``cls_name`` -> defining-class lock
        key ("RpcClient._cv") or None."""
        for info in self.mro(cls_name):
            if attr in info.lock_attrs:
                return f"{info.name}.{attr}"
        return None

    def lock_sites(self, key: str) -> list[tuple[str, int]]:
        if ":" in key:
            return self.module_lock_sites.get(key, [])
        cls, attr = key.split(".", 1)
        info = self.classes.get(cls)
        return info.lock_attrs.get(attr, []) if info else []

    def all_lock_keys(self) -> dict[str, list[tuple[str, int]]]:
        out = dict(self.module_lock_sites)
        for info in self.classes.values():
            for attr, sites in info.lock_attrs.items():
                out[f"{info.name}.{attr}"] = list(sites)
        return out

    def callees(
        self, relpath: str, cls_name: str | None, call: ast.Call
    ) -> list[OwnerKey]:
        fn = call.func
        aliases = self.module_aliases.get(relpath, {})
        if isinstance(fn, ast.Name):
            if fn.id in self.classes:
                r = self.resolve_method(fn.id, "__init__")
                return [r] if r else []
            if (relpath, fn.id) in self.mod_funcs:
                return [("f", relpath, fn.id)]
            cands = self._funcs_by_name.get(fn.id, [])
            if len(cands) == 1:  # imported plain function, unique name
                return [("f", cands[0][0], fn.id)]
            return []
        if not isinstance(fn, ast.Attribute):
            return []
        recv = fn.value
        if isinstance(recv, ast.Name):
            if recv.id == "self" and cls_name is not None:
                r = self.resolve_method(cls_name, fn.attr)
                return [r] if r else []
            if recv.id in self.global_instances:
                r = self.resolve_method(self.global_instances[recv.id], fn.attr)
                return [r] if r else []
            if recv.id in aliases:  # module alias: kv_store.push(...)
                mod = aliases[recv.id]
                if (mod, fn.attr) in self.mod_funcs:
                    return [("f", mod, fn.attr)]
            return []
        if (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
            and cls_name is not None
        ):
            # self.attr.m(): look the attr's class up in the MRO
            for info in self.mro(cls_name):
                t = info.attr_types.get(recv.attr)
                if t is not None and t in self.classes:
                    r = self.resolve_method(t, fn.attr)
                    return [r] if r else []
        return []

    # -- pass 2: transitive summaries ------------------------------------

    def summarize(
        self,
        direct: Callable[[OwnerKey, str, str | None, ast.AST], Any],
        merge: Callable[[Any, Any], Any],
        bottom: Callable[[], Any],
    ) -> dict[OwnerKey, Any]:
        """Fixpoint of per-function facts folded through the call graph.
        ``direct(owner, relpath, cls_name, fndef)`` seeds each function;
        callee facts merge in until stable."""
        bodies: dict[OwnerKey, tuple[str, str | None, ast.AST]] = {}
        for (relpath, fname), fndef in self.mod_funcs.items():
            bodies[("f", relpath, fname)] = (relpath, None, fndef)
        for info in self.classes.values():
            for mname, fndef in info.methods.items():
                bodies[("m", info.name, mname)] = (
                    info.relpath, info.name, fndef,
                )
        facts = {
            k: direct(k, rp, cn, fd) for k, (rp, cn, fd) in bodies.items()
        }
        call_edges: dict[OwnerKey, list[OwnerKey]] = {}
        for k, (rp, cn, fd) in bodies.items():
            edges = []
            for sub in ast.walk(fd):
                if isinstance(sub, ast.Call):
                    edges.extend(self.callees(rp, cn, sub))
            call_edges[k] = edges
        changed = True
        while changed:
            changed = False
            for k, edges in call_edges.items():
                cur = facts[k]
                for e in edges:
                    if e in facts:
                        nxt = merge(cur, facts[e])
                        if nxt != cur:
                            cur = nxt
                if cur != facts[k]:
                    facts[k] = cur
                    changed = True
        return facts
