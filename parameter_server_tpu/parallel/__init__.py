"""Pod runtime: mesh construction, SPMD pull/push, SSP clock, workload pool.

Reference analog: src/system/ (Postoffice node registry, Executor dependency
tracking, node groups). The N-servers x M-workers process graph collapses
onto a 2-D device mesh:

    axis "data" — worker group: each index owns a shard of examples
    axis "kv"   — server group: each index owns a contiguous key range

Push/Pull are XLA collectives on ICI instead of ZeroMQ messages; the SSP
bounded-delay clock is a host-side gate on step dispatch.
"""

# debug lock-order witness (analysis/witness.py): chaos-style opt-in —
# PS_LOCK_WITNESS=1 wraps threading.Lock/RLock/Condition construction in
# this package's modules and raises on any inversion of the statically
# derived acquisition order. Armed BEFORE the submodule imports below so
# even import-time singletons in this subpackage are instrumented.
import os as _os  # noqa: E402

if _os.environ.get("PS_LOCK_WITNESS", "") not in ("", "0"):
    from parameter_server_tpu.analysis import witness as _witness

    _witness.maybe_install_from_env()

# seeded interleaving explorer (analysis/explorer.py): PS_SCHED=<seed>
# perturbs every package lock/queue/RCU-publish boundary from per-site
# seeded streams — adversarial interleavings on demand, replayable from
# the seed. Armed after the witness so forced orders are still checked.
if _os.environ.get("PS_SCHED", "") not in ("", "0"):
    from parameter_server_tpu.analysis import explorer as _explorer

    _explorer.maybe_install_from_env()

# Eraser-style lockset race witness (analysis/racewitness.py):
# PS_RACE_WITNESS=1 tracks each thread's held locks and checks every
# access to REGISTERED shared objects (residual buffers, encode-cache
# budget, push ledger, heat sketch, key-cache generation — see
# metrics.race_track call sites) for an empty common lockset on
# conflicting pairs. Reports collect in racewitness.reports(); armed
# runs finish and then assert none. Composes over witness/explorer.
if _os.environ.get("PS_RACE_WITNESS", "") not in ("", "0"):
    from parameter_server_tpu.analysis import racewitness as _racewitness

    _racewitness.maybe_install_from_env()

from parameter_server_tpu.parallel import runtime  # noqa: F401
from parameter_server_tpu.parallel.backend import (  # noqa: F401
    PSBackend,
    SocketBackend,
    make_backend,
    train_linear,
)
from parameter_server_tpu.parallel.meshbackend import MeshBackend  # noqa: F401
from parameter_server_tpu.parallel.mesh import make_mesh  # noqa: F401
from parameter_server_tpu.parallel.runtime import Runtime  # noqa: F401
from parameter_server_tpu.parallel.spmd import (  # noqa: F401
    make_spmd_predict_step,
    make_spmd_train_multistep,
    make_spmd_train_step,
    shard_state,
    stack_batches,
    stack_step_groups,
)
from parameter_server_tpu.parallel.ssp import SSPClock  # noqa: F401
from parameter_server_tpu.parallel.workload import WorkloadPool  # noqa: F401
