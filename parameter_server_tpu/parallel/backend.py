"""Transport-neutral client data plane: the ``PSBackend`` interface.

Reference analog: ``KVVector`` — the worker-side handle an app holds,
which hides WHERE the parameter servers live (src/parameter/kv_vector.h
binds a customer id, not a transport). Here the same seam splits the two
tiers this repo grew in parallel universes:

- :class:`SocketBackend` — the cross-process wire tier: N range-sharded
  :class:`~parameter_server_tpu.parallel.multislice.ShardServer`
  processes reached through :class:`ServerHandle`\\ s, which carry the
  whole filter stack (need_keys key caching, pipelined async windows,
  quantized transport with the client error-feedback residual, the
  serving key cache, reconnect/dedup recovery). This backend owns the
  key-range fan-out that every wire client used to hand-roll: slice the
  batch's sorted unique keys against the server ranges, issue per-shard
  pulls/pushes concurrently on the async wire, merge.
- :class:`~parameter_server_tpu.parallel.meshbackend.MeshBackend` — the
  in-mesh GSPMD tier: when workers and servers share one JAX process
  mesh there is no wire at all; the KV store is ONE NamedSharding-
  sharded ``(num_keys, vdim)`` table over the ``kv`` axis, pull lowers
  to a masked local gather + psum over ICI, push to a (optionally
  int8-quantized, EQuARX-style) scatter collective applying the server
  updater as a single sharded jitted update.

Apps and benches write against the interface once; ``make_backend``
picks the transport from the ``[mesh]`` config section. The canonical
:func:`train_linear` loop below runs UNMODIFIED on either backend —
it is the loop the backend-parity tests and the ``backend`` bench cell
drive, so "same trainer, different transport" is a checked property,
not a claim.

Key contract (both backends): ``keys`` are GLOBAL key indices —
``int64``, sorted, unique, each real key at most once, all strictly
below ``num_keys`` (the localizer contract; row 0 is the pad row and
may appear only with a zero gradient). ``pull`` returns ``(U, vdim)``
float32 rows; ``push`` takes ``(U,)`` or ``(U, vdim)`` gradients.
"""

from __future__ import annotations

import abc
import threading
from concurrent.futures import Future
from typing import Any

import numpy as np


class PSBackend(abc.ABC):
    """The transport-neutral client data plane (see module docstring).

    ``push_async`` ack semantics are transport-specific — the socket
    backend resolves when every shard server ACKED the apply (the SSP
    ``PushWindow`` hangs retirement off that), the mesh backend resolves
    at dispatch (device-program order already guarantees a later pull
    sees the push) — but ``flush()`` means the same thing on both: every
    push issued so far is durably applied when it returns.
    """

    num_keys: int
    vdim: int

    @abc.abstractmethod
    def pull(self, keys: np.ndarray) -> np.ndarray:
        """Weights for global ``keys`` -> (U, vdim) float32."""

    @abc.abstractmethod
    def push(self, keys: np.ndarray, grads: np.ndarray) -> None:
        """Apply the server updater to ``keys`` with ``grads``; blocks
        until the push is accepted by the transport (NOT necessarily
        applied — see ``flush``)."""

    @abc.abstractmethod
    def pull_async(self, keys: np.ndarray) -> Future:
        """Non-blocking ``pull``; Future of the (U, vdim) rows."""

    @abc.abstractmethod
    def push_async(self, keys: np.ndarray, grads: np.ndarray) -> Future:
        """Non-blocking ``push``; Future resolves (to None) per this
        backend's ack semantics (class docstring)."""

    @abc.abstractmethod
    def flush(self) -> None:
        """Block until every push issued so far is applied."""

    @abc.abstractmethod
    def weights(self) -> np.ndarray:
        """Materialize the full (num_keys, vdim) weight table."""

    def stats(self) -> dict[str, Any]:
        return {}

    def close(self) -> None:  # noqa: B027 — optional hook
        pass

    # context-manager sugar: benches/tests hold a backend per arm
    def __enter__(self) -> "PSBackend":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _join_futures(futs: list[Future], combine) -> Future:
    """One Future resolving to ``combine([f.result() for f in futs])``
    once every input resolved; the FIRST exception wins (concurrently
    failing shards race, so the winner is decided under a lock — a
    second ``set_exception`` would raise InvalidStateError inside the
    loser's callback). Completion runs on the last-resolving future's
    callback thread, so ``combine`` must be cheap and non-blocking (a
    concat, not a wire call)."""
    out: Future = Future()
    if not futs:
        out.set_result(combine([]))
        return out
    lock = threading.Lock()
    remaining = [len(futs)]
    failed = [False]
    results: list[Any] = [None] * len(futs)

    def done(i: int, f: Future) -> None:
        try:
            results[i] = f.result()
        except BaseException as e:  # noqa: BLE001 — future boundary
            with lock:
                first = not failed[0]
                failed[0] = True
            if first:
                out.set_exception(e)
            return
        with lock:
            # a failed input never decrements, so remaining can only hit
            # zero on the all-resolved path — set_result cannot race a
            # set_exception
            remaining[0] -= 1
            last = remaining[0] == 0
        if last:
            try:
                out.set_result(combine(results))
            except BaseException as e:  # noqa: BLE001 — future boundary
                out.set_exception(e)

    for i, f in enumerate(futs):
        f.add_done_callback(lambda g, i=i: done(i, g))
    return out


class SocketBackend(PSBackend):
    """The wire tier behind the neutral interface: range-sharded
    :class:`ServerHandle`\\ s + the key-range fan-out.

    The handles keep everything the socket path earned over PRs 1-7 —
    need_keys key caching, the pipelined async window, quantized
    transport with exactly-once error-feedback residuals, serving key
    caches, reconnect-and-dedup recovery — this class only owns the
    slicing of a global key set against the server ranges and the
    concurrent per-shard issue/merge that every wire client previously
    hand-rolled (run_worker's ``segs``/``bounds`` block).
    """

    def __init__(
        self,
        handles: list,
        ranges: list,
        num_keys: int,
        vdim: int = 1,
        own_handles: bool = True,
        own_servers: list | None = None,
    ):
        """``handles[i]`` serves ``ranges[i]`` (contiguous, sorted,
        covering [0, num_keys) — the coordinator's EvenDivide output).
        ``own_handles=False`` leaves closing the handles to the caller
        (run_worker shares them with its shutdown path);
        ``own_servers`` hands the backend in-process loopback servers
        whose whole lifecycle it owns — ``close()`` sends each handle a
        shutdown and stops them (see :func:`local_socket_backend`)."""
        if len(handles) != len(ranges):
            raise ValueError(
                f"{len(handles)} handles vs {len(ranges)} ranges"
            )
        self.handles = list(handles)
        self.ranges = list(ranges)
        self.num_keys = int(num_keys)
        self.vdim = int(vdim)
        self._own = own_handles
        self._servers = list(own_servers or [])
        self._begins = np.array(
            [r.begin for r in self.ranges] + [self.num_keys], dtype=np.int64
        )
        # outstanding push futures for flush(): completed entries remove
        # themselves (keeping the set bounded by the in-flight window)
        # but a FAILURE is remembered until the next flush observes it —
        # otherwise a fire-and-forget push_async whose recovery exhausted
        # would vanish and flush() would lie about "durably applied"
        self._inflight_lock = threading.Lock()
        self._inflight: set[Future] = set()
        self._push_failure: BaseException | None = None

    def _segments(
        self, keys: np.ndarray
    ) -> tuple[list[np.ndarray], np.ndarray]:
        """Slice sorted global ``keys`` into per-shard RANGE-RELATIVE
        key arrays (the reference's parallel_ordered_match): one
        searchsorted against the range begins; the bounds come along so
        push can slice its gradient rows without a second pass."""
        keys = np.asarray(keys, dtype=np.int64)
        bounds = np.searchsorted(keys, self._begins)
        return [
            keys[bounds[s] : bounds[s + 1]] - self.ranges[s].begin
            for s in range(len(self.handles))
        ], bounds

    def pull_async(self, keys: np.ndarray) -> Future:
        segs, _bounds = self._segments(keys)
        futs = [
            h.pull_async(seg) for h, seg in zip(self.handles, segs)
        ]
        u, vdim = len(keys), self.vdim

        def combine(rows: list) -> np.ndarray:
            flat = (
                np.concatenate([np.asarray(r).ravel() for r in rows])
                if rows
                else np.zeros(0, np.float32)
            )
            return flat.astype(np.float32, copy=False).reshape(u, vdim)

        return _join_futures(futs, combine)

    def pull(self, keys: np.ndarray) -> np.ndarray:
        return self.pull_async(keys).result()

    def push_async(self, keys: np.ndarray, grads: np.ndarray) -> Future:
        segs, bounds = self._segments(keys)
        g = np.asarray(grads, dtype=np.float32).reshape(len(keys), -1)
        futs = [
            h.push_async(seg, g[bounds[s] : bounds[s + 1]])
            for s, (h, seg) in enumerate(zip(self.handles, segs))
        ]
        out = _join_futures(futs, lambda _res: None)
        with self._inflight_lock:
            self._inflight.add(out)

        def _retire(f: Future) -> None:
            exc = f.exception()
            with self._inflight_lock:
                self._inflight.discard(out)
                if exc is not None and self._push_failure is None:
                    self._push_failure = exc

        out.add_done_callback(_retire)
        return out

    def push(self, keys: np.ndarray, grads: np.ndarray) -> None:
        self.push_async(keys, grads).result()

    def flush(self) -> None:
        """Block until every push issued so far settled; raise the first
        failure among them (even one whose future nobody retained) —
        "returned cleanly" must mean "durably applied", not "the failed
        futures already removed themselves"."""
        from concurrent.futures import wait as _wait

        while True:
            with self._inflight_lock:
                pending = list(self._inflight)
                if not pending:
                    exc, self._push_failure = self._push_failure, None
                    break
            _wait(pending)
        if exc is not None:
            raise exc

    def weights(self) -> np.ndarray:
        w = np.zeros((self.num_keys, self.vdim), dtype=np.float32)
        for h in self.handles:
            begin, rows = h.dump()
            rows = np.asarray(rows, np.float32).reshape(-1, self.vdim)
            w[begin : begin + len(rows)] = rows
        return w

    def stats(self) -> dict[str, Any]:
        return {
            "backend": "socket",
            "shards": [h.stats() for h in self.handles],
        }

    def close(self) -> None:
        self.flush()
        if self._servers:
            # owned loopback servers stop on the shutdown command (the
            # same discipline every ShardServer test uses)
            for h in self.handles:
                try:
                    h.shutdown()
                except Exception:  # noqa: BLE001 — server already gone
                    pass
        if self._own:
            for h in self.handles:
                h.close()


def local_socket_backend(
    make_updater,
    num_keys: int,
    num_servers: int = 2,
    cfg=None,
    vdim: int = 1,
) -> SocketBackend:
    """Spin up ``num_servers`` in-process loopback ShardServers over an
    even key-range divide and wire connected handles into a
    SocketBackend that OWNS them — ``close()`` shuts the servers down.
    The one assembly the bench's socket arms, ``cli backend`` and the
    parity tests all share (a real deployment's topology comes from the
    coordinator instead; see ``_connect_servers``)."""
    from parameter_server_tpu.parallel.multislice import (
        ServerHandle,
        ShardServer,
    )
    from parameter_server_tpu.utils.config import PSConfig
    from parameter_server_tpu.utils.keyrange import KeyRange

    cfg = cfg or PSConfig()
    ranges = KeyRange(0, num_keys).even_divide(max(1, num_servers))
    servers = [
        ShardServer(
            make_updater(), r, server_cfg=cfg.server, serve_cfg=cfg.serve
        ).start()
        for r in ranges
    ]
    handles = [
        ServerHandle(s.address, i, 0, cfg, range_size=r.size, key_range=r)
        for i, (s, r) in enumerate(zip(servers, ranges))
    ]
    return SocketBackend(
        handles, ranges, num_keys, vdim=vdim, own_servers=servers
    )


def make_backend(cfg, updater=None, handles=None, ranges=None) -> PSBackend:
    """Build the configured backend from the ``[mesh]`` section.

    ``backend = "mesh"`` needs only the config (the table lives in this
    process's device mesh); ``"socket"`` additionally needs the connected
    ``handles`` + their ``ranges`` (the wire tier's topology is the
    coordinator's business, not the config file's)."""
    kind = cfg.mesh.backend
    if kind == "mesh":
        from parameter_server_tpu.parallel.meshbackend import MeshBackend

        if updater is None:
            from parameter_server_tpu.models.linear import updater_from_config

            updater = updater_from_config(cfg)
        return MeshBackend(
            updater,
            cfg.data.num_keys,
            kv_shards=cfg.mesh.kv_shards or None,
            quant=cfg.mesh.quant,
            quant_seg=cfg.mesh.quant_seg,
        )
    if kind == "socket":
        if handles is None or ranges is None:
            raise ValueError(
                "[mesh] backend='socket' needs connected server handles + "
                "ranges (see multislice._connect_servers)"
            )
        return SocketBackend(handles, ranges, cfg.data.num_keys)
    raise ValueError(
        f"[mesh] backend must be 'socket' or 'mesh', got {kind!r}"
    )


def train_linear(
    backend: PSBackend,
    kb_all: np.ndarray,
    y_all: np.ndarray,
    batch_size: int,
    progress_from: float = 0.5,
) -> dict[str, Any]:
    """The canonical backend-agnostic linear trainer loop: per batch,
    pull touched weights -> logistic loss -> per-key mean gradient ->
    push. ONE implementation drives both the backend-parity tests and
    the ``backend`` bench cell, so the two transports are compared on
    literally the same client code.

    ``kb_all``: (N, nnz) feature indices in [0, num_keys - 2) — shifted
    by +1 on the wire so row 0 stays the pad row. ``y_all``: (N,) 0/1
    labels. Returns progressive-validation AUC over the stream's tail
    (from ``progress_from`` onward) plus the per-example probabilities
    (for exactness assertions between backends)."""
    from parameter_server_tpu.models import metrics as M

    n, nnz = kb_all.shape
    n_batches = n // batch_size
    start_prog = int(n_batches * progress_from)
    ys: list[np.ndarray] = []
    ps: list[np.ndarray] = []
    for b in range(n_batches):
        s = slice(b * batch_size, (b + 1) * batch_size)
        kb, y = kb_all[s], y_all[s]
        uniq, inv = np.unique(kb, return_inverse=True)
        keys = (uniq + 1).astype(np.int64)  # row 0 = pad row
        w = backend.pull(keys).astype(np.float64).reshape(-1)
        logit = w[inv.reshape(batch_size, nnz)].sum(axis=1)
        p = 1.0 / (1.0 + np.exp(-logit))
        err = p - y
        g = np.zeros(len(uniq))
        np.add.at(
            g, inv.reshape(batch_size, nnz).ravel(), np.repeat(err, nnz)
        )
        backend.push(keys, (g / batch_size).astype(np.float32))
        if b >= start_prog:
            ys.append(np.asarray(y, np.float64))
            ps.append(p)
    backend.flush()
    y_cat = np.concatenate(ys) if ys else np.zeros(0)
    p_cat = np.concatenate(ps) if ps else np.zeros(0)
    return {
        "auc": float(M.auc(y_cat, p_cat)) if len(y_cat) else float("nan"),
        "examples": n_batches * batch_size,
        "probs": p_cat,
    }
