"""PodTrainer: the multi-worker training driver.

Reference analog: the whole runtime stack working together — scheduler
assigns file shards (WorkloadPool), M workers stream minibatches and
Push/Pull against N servers (the SPMD step over the data x kv mesh),
bounded-delay consistency (SSPClock), merged Progress at the scheduler
(ProgressReporter), heartbeats.

SSP on a pod, concretely: collectives make each *global* step synchronous
across the mesh, so per-worker staleness lives in two places —
  1. within a step, every worker's gradient is computed against step-start
     weights and pushes land sequentially (parallel.spmd), and
  2. across steps, the host DISPATCHES up to ``max_delay + 1`` steps before
     blocking on completed results (JAX async dispatch gives the overlap,
     the SSPClock bounds the run-ahead — the Executor wait_time analog).
max_delay = 0 is BSP-with-pipelining-of-one; larger values overlap more
host batch-prep with device compute."""

from __future__ import annotations

import itertools
import time
from collections.abc import Iterator
from typing import Any

import jax
import numpy as np

from parameter_server_tpu.data.batch import BatchBuilder, CSRBatch
from parameter_server_tpu.data.pipeline import PrefetchPipeline
from parameter_server_tpu.data.reader import MinibatchReader
from parameter_server_tpu.models import metrics as M
from parameter_server_tpu.models.linear import updater_from_config
from parameter_server_tpu.parallel.mesh import make_mesh
from parameter_server_tpu.parallel.runtime import Runtime
from parameter_server_tpu.parallel.spmd import (
    make_spmd_predict_step,
    make_spmd_train_multistep,
    make_spmd_train_step,
    padded_num_keys,
    stack_batches,
    stack_step_groups,
)
from parameter_server_tpu.parallel.ssp import DispatchWindow, SSPClock
from parameter_server_tpu.parallel.workload import WorkloadPool
from parameter_server_tpu.utils import flightrec, trace
from parameter_server_tpu.utils.config import PSConfig
from parameter_server_tpu.utils.metrics import ProgressReporter, timers


# process-wide trainer sequence for control-plane KV namespacing (see
# PodTrainer._bucket_ns)
_TRAINER_SEQ = itertools.count()

# lower bound on the bucket-agreement probe window: real pods need room
# for ordinary startup skew whatever fault.startup_grace_s says; tests
# shrink it to exercise the timeout diagnostic without a 2-minute wait
_PROBE_GRACE_FLOOR_S = 120.0

# eval's bounded async-dispatch depth (see PodTrainer.evaluate_files):
# enough to overlap host batch-build with device predict, small enough
# that queued input/result buffers stay a constant HBM footprint
_EVAL_INFLIGHT = 2


class _WorkerStream:
    """One logical worker's batch source: drains workloads (files) from the
    pool, reading each through a MinibatchReader (ref: SGD workers asking
    the scheduler for the next file shard)."""

    def __init__(
        self, worker_id: int, pool: WorkloadPool, fmt: str, builder: BatchBuilder,
        backend: str = "auto",
    ):
        self.worker_id = worker_id
        self.pool = pool
        self.fmt = fmt
        self.builder = builder
        self.backend = backend
        self._iter: Iterator[CSRBatch] | None = None
        self._current: str | None = None

    def next_batch(self) -> CSRBatch | None:
        while True:
            if self._iter is not None:
                b = next(self._iter, None)
                if b is not None:
                    return b
                if self._current is not None:
                    self.pool.finish(self._current)
                self._iter = None
                self._current = None
            w = self.pool.fetch(self.worker_id)
            if w is None:
                return None
            self._current = w
            self._iter = iter(
                MinibatchReader(
                    [self._reader_path(w)], self.fmt, self.builder,
                    backend=self.backend,
                )
            )

    def _reader_path(self, workload: str) -> str:
        """Map a pool item to the file it names (identity here; the
        dynamic-pool stream carries an epoch prefix)."""
        return workload

    def _empty(self) -> CSRBatch:
        """Inert batch (all padding) for a drained worker: contributes no
        loss, no gradient."""
        return self.builder.build(np.zeros(0, dtype=np.float32), [], [])


class _RemotePool:
    """WorkloadPool facade over the TCP Coordinator: the wire tier's
    scheduler assigns shards across SPMD hosts (tier composition)."""

    def __init__(self, ctl):
        self._ctl = ctl

    def fetch(self, worker: int) -> str | None:
        return self._ctl.workload_fetch(worker)

    def finish(self, workload: str) -> None:
        self._ctl.workload_finish(workload)


class _EpochStream(_WorkerStream):
    """_WorkerStream whose pool items are ``"<epoch>:<path>"`` (epochs ride
    the dynamic pool as distinct workloads)."""

    def _reader_path(self, workload: str) -> str:
        return workload.split(":", 1)[1]


class PodTrainer:
    """Train the flagship sparse-LR app across a data x kv device mesh."""

    def __init__(
        self,
        cfg: PSConfig,
        mesh=None,
        reporter: ProgressReporter | None = None,
        runtime: Runtime | None = None,
        profile_dir: str = "",
    ):
        self.cfg = cfg
        if cfg.trace.trace_dir and not trace.tracer.enabled:
            # config-armed tracing for the in-process pod path (spawned
            # nodes arm via run_node / PS_TRACE_DIR instead)
            trace.configure(
                cfg.trace.trace_dir, capacity=cfg.trace.capacity,
                process_name="pod-trainer",
            )
        if runtime is not None:
            self.runtime = runtime
        else:
            m = mesh or make_mesh(cfg.parallel.data_shards, cfg.parallel.kv_shards)
            self.runtime = Runtime(
                mesh=m,
                process_index=0,
                process_count=1,
                data_shards=m.shape["data"],
                kv_shards=m.shape["kv"],
                local_data_shards=m.shape["data"],
            )
        self.mesh = self.runtime.mesh
        # one source of truth (ref: the scheduler validating -num_servers /
        # -num_workers against the registered cluster): a cfg whose
        # parallel section disagrees with the mesh it runs on must fail
        # loudly, not train silently under different sharding
        got = (self.mesh.shape["data"], self.mesh.shape["kv"])
        want = (cfg.parallel.data_shards, cfg.parallel.kv_shards)
        if (mesh is not None or runtime is not None) and got != want:
            raise ValueError(
                f"cfg.parallel says (data_shards, kv_shards)={want} but the "
                f"provided {'runtime' if runtime is not None else 'mesh'} is "
                f"{got}; update cfg.parallel (or build the runtime with "
                "runtime.init(..., cfg=cfg)) so both agree"
            )
        # multi-host bucketing: shapes are sized per host, but SPMD demands
        # identical shapes (and programs) on every process per step — a
        # tiny per-step cross-host max-agreement re-pads every host to the
        # pod max bucket (see _agree_bucket). The agreement rides the
        # coordination-service KV (control plane) when available, which
        # keeps SSP run-ahead alive; the device-allgather fallback caps
        # run-ahead at 1 because it syncs the dispatch thread to the
        # device stream.
        self._bucket_sync = (
            cfg.data.bucket_nnz and self.runtime.process_count > 1
        )
        # KV-key namespacing: trainers are constructed in the same order
        # on every process (the SPMD same-program contract), so a
        # process-wide counter yields pod-agreed, collision-free
        # namespaces; epochs within a trainer get their own sub-counter
        self._bucket_ns = f"t{next(_TRAINER_SEQ)}"
        self._epoch_seq = itertools.count()
        if self._bucket_sync:
            # the probe doubles as a fail-fast check of the namespacing
            # contract: _TRAINER_SEQ only yields pod-agreed namespaces when
            # every process constructs its PodTrainers in the same order.
            # An asymmetric construction makes the probe tags disagree, so
            # the blocking get would time out — surface that as a clear
            # contract error within the startup-grace window, not a
            # 10-minute silent hang on the first training step. The window
            # is bounded below (_PROBE_GRACE_FLOOR_S) so ordinary
            # cross-process startup skew (slow checkpoint load on one
            # host) isn't misdiagnosed, and the wait is 2x that window in
            # ONE cp_allmax call: a transiently slow host then simply
            # arrives mid-wait and the blocking get completes — a true
            # rendezvous, where a retry under a fresh tag could never
            # meet a peer still posting under the first tag (and a
            # re-post under the SAME tag errors: set-once KV keys).
            grace_ms = int(
                max(_PROBE_GRACE_FLOOR_S, cfg.fault.startup_grace_s * 2)
                * 1000
            )
            try:
                probe = self.runtime.cp_allmax(
                    f"{self._bucket_ns}probe/0", (0,),
                    timeout_ms=2 * grace_ms,
                )
            except Exception as e:
                raise RuntimeError(
                    f"pod bucket-agreement probe for trainer namespace "
                    f"{self._bucket_ns!r} failed ({e!r}). If the other "
                    "processes are alive, the likely cause is processes "
                    "constructing PodTrainers in different orders (the KV "
                    "namespacing contract) — make every process build the "
                    "same trainers in the same sequence. A process that "
                    f"is merely >{2 * grace_ms // 1000}s slower to "
                    "construct its trainer also trips this; raise "
                    "fault.startup_grace_s if that is legitimate in your "
                    "deployment"
                ) from e
            if probe is None and cfg.solver.max_delay > 0:
                print(
                    "[pod] note: no control-plane KV — multi-host "
                    "bucket_nnz agreement falls back to a device "
                    "allgather, capping dispatch run-ahead at 1; "
                    f"max_delay {cfg.solver.max_delay} will not add "
                    "overlap",
                    flush=True,
                )
        self.data_shards = self.mesh.shape["data"]
        # this process feeds only its own data rows (multi-host contract)
        self.local_data_shards = self.runtime.local_data_shards
        self.updater = updater_from_config(cfg)
        # K microsteps scanned per device call (see SolverConfig.steps_per
        # _call): amortizes the per-call host->device round-trip floor
        if cfg.solver.steps_per_call < 1:
            raise ValueError(
                f"solver.steps_per_call must be >= 1, got "
                f"{cfg.solver.steps_per_call}"
            )
        self.steps_per_call = cfg.solver.steps_per_call
        if cfg.data.wire_values not in ("f32", "f16"):
            raise ValueError(
                f"data.wire_values must be 'f32' or 'f16', got "
                f"{cfg.data.wire_values!r}"
            )
        maker = (
            make_spmd_train_multistep
            if self.steps_per_call > 1
            else make_spmd_train_step
        )
        self.step_fn = maker(
            self.updater, self.mesh, cfg.data.num_keys,
            push_mode=cfg.parallel.push_mode,
        )
        self.predict_fn = make_spmd_predict_step(
            self.updater, self.mesh, cfg.data.num_keys
        )
        # table rows are num_keys rounded up to the kv-axis multiple (pad
        # rows stay exactly zero — no batch key ever reaches them), so
        # arbitrary num_keys run on any mesh shape
        self._table_rows = padded_num_keys(
            cfg.data.num_keys, self.mesh.shape["kv"]
        )
        self.state = self.runtime.init_state(
            lambda: self.updater.init(self._table_rows, 1)
        )
        self.reporter = reporter or ProgressReporter()
        self.clock = SSPClock(
            num_workers=1, max_delay=max(cfg.solver.max_delay, 0)
        )
        self.examples_seen = 0
        # observability: peak dispatch run-ahead (the SSP/async-overlap
        # depth actually reached; == max_delay + 1 when the gate binds)
        self.max_inflight = 0
        # observability (SURVEY §5.1): jax.profiler traces on demand + the
        # static per-step collective-byte estimate in every report (the
        # reference's Postoffice byte counters; reconcile the estimate
        # against profiler-measured collective sizes on real hardware)
        self.profile_dir = profile_dir
        from parameter_server_tpu.parallel.traffic import linear_step_traffic

        cap = min(
            cfg.solver.minibatch * cfg.data.max_nnz_per_example + 1,
            cfg.data.num_keys,
        )
        self.est_step_traffic = linear_step_traffic(
            unique_capacity=cap,
            vdim=1,
            data_shards=self.data_shards,
            kv_shards=self.mesh.shape["kv"],
            push_mode=cfg.parallel.push_mode,
            num_keys=cfg.data.num_keys,
        )

    def _builder(self, key_mode: str) -> BatchBuilder:
        from parameter_server_tpu.data.batch import training_builder

        return training_builder(self.cfg, key_mode)

    def train_files(
        self,
        files: list[str],
        key_mode: str = "hash",
        report_every: int = 20,
    ) -> dict:
        """Run all epochs over ``files`` sharded across workers."""
        with self._trace_cm():
            return self._run_epochs(files, key_mode, report_every)

    def _trace_cm(self):
        import contextlib

        return (
            jax.profiler.trace(self.profile_dir)
            if self.profile_dir
            else contextlib.nullcontext()
        )

    def train_files_dynamic(
        self,
        files: list[str],
        coordinator: str,
        key_mode: str = "hash",
        report_every: int = 20,
    ) -> dict:
        """Compose the two multi-process tiers (SURVEY §2.8/§5.8): the TCP
        tier's Coordinator hands file shards to SPMD hosts DYNAMICALLY
        (the reference scheduler's WorkloadPool, instead of this module's
        static per-host split), while the data plane stays XLA collectives
        over the (data, kv) mesh. A fast host simply fetches more shards;
        a host that drains early keeps issuing inert steps until the
        pod-wide example count hits zero (the existing termination
        contract — dynamic assignment needs no new synchronization).

        Process 0 must be running the Coordinator (or anything hosting
        its protocol) at ``coordinator``; EVERY process calls this with
        the same file list. Epochs ride the pool as distinct items."""
        from parameter_server_tpu.parallel.control import ControlClient
        from parameter_server_tpu.utils.metrics import wire_counters

        cfg = self.cfg
        # self-healing client: a coordinator restart or injected control-
        # plane fault mid-run is absorbed by reconnect + resend (the
        # server-side reply cache keeps workload_fetch exactly-once)
        ctl = ControlClient(
            coordinator, reconnect_timeout_s=cfg.fault.reconnect_timeout_s
        )
        try:
            items = [
                f"{e}:{f}"
                for e in range(max(1, cfg.solver.epochs))
                for f in sorted(files)
            ]
            if self.runtime.process_index == 0:
                ctl.workload_init(items)
                # workload_init is first-wins on the Coordinator: a pool
                # someone else already initialized (a second dynamic run,
                # or the wire tier's scheduler) would be silently reused
                # and this pod would train on nothing — fail loudly
                st = ctl.workload_stats()
                total = st["pending"] + st["active"] + st["done"]
                if total != len(items) or st["done"] or st["active"]:
                    raise RuntimeError(
                        f"coordinator at {coordinator} already holds a "
                        f"workload pool ({st}); train_files_dynamic needs "
                        "a fresh Coordinator per run"
                    )
                ctl.kv_set("pod_pool_ready")
            else:
                ctl.kv_get("pod_pool_ready", block=True, timeout=120)
            pool = _RemotePool(ctl)
            streams = [
                _EpochStream(
                    self.runtime.process_index * self.local_data_shards + w,
                    pool, cfg.data.format, self._builder(key_mode),
                )
                for w in range(self.local_data_shards)
            ]
            with self._trace_cm():
                out = dict(self._train_epoch(streams, report_every) or {})
            # recovery observability for the pod path (cumulative for this
            # process; mostly zero on a healthy wire)
            out["rpc_retries"] = wire_counters.get("rpc_retries")
            out["rpc_reconnects"] = wire_counters.get("rpc_reconnects")
            return out
        finally:
            ctl.close()

    def _run_epochs(self, files, key_mode, report_every) -> dict:
        cfg = self.cfg
        last: dict = {}
        for _ in range(max(1, cfg.solver.epochs)):
            # per-host pool over this host's local data rows. Contract:
            # callers pass the FULL file list on every host; the trainer
            # applies runtime.shard_files exactly once here (pre-sharding
            # upstream would double-shard and silently drop files)
            pool = WorkloadPool(self.runtime.shard_files(files))
            streams = [
                _WorkerStream(w, pool, cfg.data.format, self._builder(key_mode))
                for w in range(self.local_data_shards)
            ]
            last = self._train_epoch(streams, report_every) or last
        return last

    @staticmethod
    def _assemble_group(items: list[tuple]) -> tuple:
        """Combine K prepared step items into one multistep dispatch item
        (runs on the pipeline's stacker thread, never the dispatch loop):
        (stacked (D, K, ...), total examples, per-microstep metas)."""
        stacked = stack_step_groups([it[0] for it in items])
        n = sum(it[1] for it in items)
        metas = [(it[2], it[3]) for it in items]
        return stacked, n, metas

    def _prepare(self, batches: list[CSRBatch]) -> tuple:
        """Per-step host work: stack D per-worker batches + bookkeeping.
        Runs on the pipeline's stacker thread (or inline when serial).
        Bucketed batches are first re-padded to the group max (buckets are
        powers of two, so group shapes stay a small compiled set)."""
        from parameter_server_tpu.data.batch import pad_group

        stacked = stack_batches(
            pad_group(batches), None,
            compact=self.cfg.data.compact_wire,
            values_f16=self.cfg.data.wire_values == "f16",
        )
        n = sum(b.num_examples for b in batches)
        labels = np.concatenate([b.labels[: b.num_examples] for b in batches])
        counts = [b.num_examples for b in batches]
        return stacked, n, labels, counts

    def _agree_bucket(self, stacked: dict, tag: str) -> dict:
        """Pod-wide bucket agreement for bucketed batches: max-reduce
        every host's local (nnz, unique) shape and zero-pad up to the pod
        max. Buckets are powers of two, so the agreed set of shapes (and
        compiled programs) stays small pod-wide.

        The reduce rides the coordination-service KV (Runtime.cp_allmax)
        — pure control plane, so the dispatch thread keeps its SSP
        run-ahead. Fallback (no distributed client): a device allgather,
        which blocks this thread on the device stream and caps run-ahead
        at 1 regardless of max_delay (warned at init)."""
        from parameter_server_tpu.data.batch import zero_extend

        # trailing axis is the variable one for both single-step (D, NNZ)
        # and multistep-group (D, K, NNZ) stacks
        local = (
            stacked["values"].shape[-1], stacked["unique_keys"].shape[-1],
        )
        agreed = self.runtime.cp_allmax(tag, local)
        if agreed is None:
            from jax.experimental import multihost_utils

            agreed = (
                np.asarray(
                    multihost_utils.process_allgather(
                        np.array(local, dtype=np.int32)
                    )
                )
                .reshape(-1, 2)
                .max(axis=0)
            )
        nnz_t, u_t = agreed
        out = {
            **stacked,
            "unique_keys": zero_extend(stacked["unique_keys"], int(u_t), axis=-1),
            "local_ids": zero_extend(stacked["local_ids"], int(nnz_t), axis=-1),
            "values": zero_extend(stacked["values"], int(nnz_t), axis=-1),
        }
        if "row_ids" in stacked:  # absent in the compact wire format
            out["row_ids"] = zero_extend(stacked["row_ids"], int(nnz_t), axis=-1)
        return out

    def _train_epoch(self, streams: list[_WorkerStream], report_every: int) -> dict:
        window: list = []
        n_since = 0
        t0 = time.perf_counter()
        step_idx = 0
        last: dict = {}
        drained = False  # a retired step reported 0 pod-wide examples
        # per-epoch control-plane KV namespace (pod-agreed; see _bucket_ns)
        bkt_gen = f"{self._bucket_ns}e{next(self._epoch_seq)}"

        def _retire(step: int, entry) -> None:
            nonlocal drained
            loss_arr, examples_arr, probs, metas, n = entry
            # np.asarray blocks until the device call is done (the SSP
            # bound taking effect); single-step outputs are scalars,
            # multistep outputs carry a (K,) microstep axis
            with trace.span("step.retire", cat="step", step=step), \
                    timers.timer("trainer.retire"):
                losses = np.atleast_1d(np.asarray(loss_arr))
                exs = np.atleast_1d(np.asarray(examples_arr))
            # flight recorder: the trainer's dispatch/retire cadence —
            # "which step was in flight when the pod wedged"
            flightrec.record("step.retire", step=step, examples=int(n))
            self.clock.finish(0, step)
            # empties only ever trail real batches within a group, so the
            # LAST microstep's pod-wide count is the drained signal
            if float(exs[-1]) == 0.0:
                drained = True
            probs_l = self.runtime.localize_data(probs)  # (Dl, [K,] B)
            if probs_l.ndim == 2:
                probs_l = probs_l[:, None, :]
            for k, meta in enumerate(metas):
                window.append((float(losses[k]), probs_l[:, k, :], meta))

        gate = DispatchWindow(self.clock.max_delay, _retire)
        K = self.steps_per_call

        # Host input pipeline (ref: learner/sgd.h parser threads): batch
        # builds run on background threads — with K > 1 the K-way group
        # stacking too (pipeline group_size/assemble) — so the loop below
        # only pops ready dispatch items and issues the device call.
        depth = self.cfg.data.pipeline_depth
        pipeline = (
            PrefetchPipeline(
                streams, self._prepare, depth=depth,
                group_size=K,
                assemble=self._assemble_group if K > 1 else None,
            )
            if depth > 0
            else None
        )
        empty_item = None  # lazily-built inert step item for drained hosts
        empty_group = None  # its assembled K-group form

        def _serial_item():
            batches = [s.next_batch() for s in streams]
            if not any(b is not None for b in batches):
                return None
            return self._prepare(
                [
                    b if b is not None else streams[i]._empty()
                    for i, b in enumerate(batches)
                ]
            )

        def _empty_single():
            nonlocal empty_item
            if empty_item is None:
                empty_item = self._prepare([s._empty() for s in streams])
            return empty_item

        def _empty_dispatch():
            nonlocal empty_group
            if K == 1:
                return _empty_single()
            if empty_group is None:
                empty_group = self._assemble_group([_empty_single()] * K)
            return empty_group

        def _next_item():
            """Next dispatch item: a prepared step (K == 1) or an
            assembled K-group. Never None — drained hosts keep issuing
            inert items so every host runs the same collectives until the
            pod-wide count hits 0."""
            if pipeline is not None:
                item = pipeline.get()
                return item if item is not None else _empty_dispatch()
            # serial (pipeline_depth=0) debug path: build inline
            if K == 1:
                return _serial_item() or _empty_single()
            singles = [_serial_item() for _ in range(K)]
            if all(s is None for s in singles):
                return _empty_dispatch()
            singles = [s if s is not None else _empty_single() for s in singles]
            return self._assemble_group(singles)

        # Termination contract (multi-host safe): a host whose local
        # streams dry up keeps issuing steps with all-empty batches — every
        # process must issue the same collectives — and ALL hosts stop
        # after retiring the first step whose pod-wide example count
        # (psum'd inside the step) is zero. The SSP gate's retirement
        # schedule is deterministic, so every host stops at the same step
        # index with no blocking host-side barrier on the dispatch path.
        try:
            while True:
                # SSP gate: block until call (t - tau - 1) fully completed
                # (with K > 1 the gate counts device CALLS, each K
                # microsteps deep — the documented steps_per_call contract)
                gate.gate(step_idx)
                if drained:
                    break
                # step anatomy: fetch (host pipeline pop) vs dispatch
                # (bucket agreement + H2D + device-call issue) — named
                # timers feed the telemetry snapshot, spans the timeline
                with trace.span("step.fetch", cat="step", step=step_idx), \
                        timers.timer("trainer.fetch"):
                    if K == 1:
                        stacked_np, n, labels, mask_counts = _next_item()
                        metas = [(labels, mask_counts)]
                    else:
                        stacked_np, n, metas = _next_item()
                with trace.span("step.dispatch", cat="step", step=step_idx), \
                        timers.timer("trainer.dispatch"):
                    if self._bucket_sync:
                        stacked_np = self._agree_bucket(
                            stacked_np, f"{bkt_gen}/{step_idx}"
                        )
                    stacked = self.runtime.globalize_batch(stacked_np)
                    # push_seed varies per microstep so quantized-push
                    # stochastic rounding never reuses a key (traced
                    # scalar: no recompile); step_idx * K is this call's
                    # first microstep index
                    self.state, out = self.step_fn(
                        self.state, stacked, step_idx * K
                    )
                flightrec.record("step.dispatch", step=step_idx, examples=int(n))
                self.examples_seen += n
                n_since += n
                gate.add(
                    step_idx,
                    (
                        out["loss_sum"], out["examples"], out["probs"],
                        metas, n,
                    ),
                )
                self.max_inflight = max(self.max_inflight, gate.max_inflight)
                step_idx += 1
                if step_idx % report_every == 0:
                    gate.drain()
                    last = self._flush(window, n_since, t0)
                    window, n_since, t0 = [], 0, time.perf_counter()
            gate.wait_all()  # epoch sync point: every dispatched step retired
        finally:
            if pipeline is not None:
                pipeline.close()
        if n_since:
            last = self._flush(window, n_since, t0)
        return last

    def _flush(self, window, n_since: int, t0: float) -> dict:
        losses = sum(w[0] for w in window)
        ys, ps = [], []
        for _, probs, (labels, counts) in window:
            off = 0
            for d, c in enumerate(counts):
                ps.append(probs[d, :c])
            ys.append(labels)
        y = np.concatenate(ys) if ys else np.zeros(0)
        p = np.concatenate(ps) if ps else np.zeros(0)
        return self.reporter.report(
            examples=self.examples_seen,
            objv=losses / max(n_since, 1),
            auc=M.auc(y, p) if len(y) else float("nan"),
            ex_per_sec=n_since / max(time.perf_counter() - t0, 1e-9),
            ssp=self.clock.progress(),
            # static per-device collective estimate for this window (ref:
            # Postoffice byte counters; see traffic.py)
            est_collective_bytes=self.est_step_traffic.total_bytes
            * len(window),
        )

    def full_weights(self) -> np.ndarray:
        """Materialize the (num_keys, vdim) weight vector on this host from
        its local replica of the kv-sharded state."""
        import jax.numpy as jnp

        host = self.runtime.state_to_host(self.state)
        return np.asarray(
            self.updater.weights({k: jnp.asarray(v) for k, v in host.items()})
        )[: self.cfg.data.num_keys]

    def save(self, ckpt_dir, meta: dict | None = None) -> None:
        """Per-host sharded checkpoint (each host writes its key-range
        slice; ref: each server dumps its own range).

        Multi-host contract: ``save`` ends in a cross-host barrier, so
        EVERY process must call it with the same decision to save — run
        the identical CLI flags (--ckpt_dir in particular) on all hosts,
        or a saving host deadlocks waiting on one that skipped it."""
        self.runtime.save_checkpoint(
            ckpt_dir,
            self.state,
            meta={"examples_seen": self.examples_seen, **(meta or {})},
        )
        self.runtime.barrier("ckpt_saved")

    def load(self, ckpt_dir) -> dict:
        self.state, meta = self.runtime.load_checkpoint(ckpt_dir)
        rows = next(iter(self.state.values())).shape[0]
        if rows != self._table_rows:
            # a checkpoint written on a different mesh shape (or before
            # padding existed) carries a different pad tail: re-pad the
            # host replica up to THIS mesh's table rows
            from parameter_server_tpu.kv.store import pad_state_rows

            host = self.runtime.state_to_host(self.state)
            host = {
                k: np.asarray(v)[: self.cfg.data.num_keys]
                for k, v in host.items()
            }
            import jax.numpy as jnp

            host = pad_state_rows(
                {k: jnp.asarray(v) for k, v in host.items()},
                self._table_rows,
            )
            self.state = self.runtime.state_from_host(
                {k: np.asarray(v) for k, v in host.items()}
            )
        self.examples_seen = int(meta.get("examples_seen", 0))
        return meta

    def evaluate_files(self, files: list[str], key_mode: str = "hash") -> dict:
        """Pod-wide batch evaluation using the predict step on shard 0's
        stream layout (eval is read-only; one worker suffices)."""
        if self.runtime.process_count > 1:
            # multi-host: evaluate host-locally against the full weight
            # vector (every host holds a complete replica) — no cross-host
            # collectives, so hosts may evaluate different file sets
            from parameter_server_tpu.models.evaluation import evaluate_model

            return evaluate_model(
                self.full_weights().ravel(),
                files,
                self.cfg.data.format,
                self.cfg.data.num_keys,
                batch_size=self.cfg.solver.minibatch,
                max_nnz_per_example=self.cfg.data.max_nnz_per_example,
                key_mode=key_mode,
            )
        from parameter_server_tpu.data.batch import eval_builder

        builder = eval_builder(self.cfg, key_mode)
        reader = MinibatchReader(files, self.cfg.data.format, builder)
        # bounded async dispatch (the train loop's DispatchWindow pattern):
        # up to EVAL_INFLIGHT predicts ride JAX async dispatch — no
        # host<->device sync per D-group — while retirement of the oldest
        # keeps queued input/result buffers from accumulating in HBM
        # without bound on large eval sets
        pending: list[tuple[Any, list[np.ndarray]]] = []
        ys: list[np.ndarray] = []
        ps: list[np.ndarray] = []

        def _retire_oldest() -> None:
            probs_dev, labels_list = pending.pop(0)
            probs = np.asarray(probs_dev)  # sync point, bounded by depth
            for d, labels in enumerate(labels_list):
                ps.append(probs[d, : len(labels)])
                ys.append(labels)

        def _dispatch(group: list[CSRBatch]) -> None:
            from parameter_server_tpu.data.batch import pad_group

            # fill every data shard with real batches (D at a time); only
            # the tail group pads with inert batches
            batches = pad_group(
                group
                + [
                    _pad_like(builder)
                    for _ in range(self.data_shards - len(group))
                ]
            )
            probs_dev = self.predict_fn(
                self.state,
                stack_batches(
                    batches, self.mesh,
                    compact=self.cfg.data.compact_wire,
                    values_f16=self.cfg.data.wire_values == "f16",
                ),
            )
            pending.append(
                (probs_dev, [b.labels[: b.num_examples] for b in group])
            )
            if len(pending) >= _EVAL_INFLIGHT:
                _retire_oldest()

        group: list[CSRBatch] = []
        for b in reader:
            group.append(b)
            if len(group) == self.data_shards:
                _dispatch(group)
                group = []
        if group:
            _dispatch(group)
        while pending:
            _retire_oldest()
        y = np.concatenate(ys)
        p = np.concatenate(ps)
        return {"auc": M.auc(y, p), "logloss": M.logloss(y, p), "examples": len(y)}


def _pad_like(builder: BatchBuilder) -> CSRBatch:
    return builder.build(np.zeros(0, dtype=np.float32), [], [])
