"""Device mesh construction (reference analog: the scheduler's node-id /
key-range assignment at startup, src/system/ manager+postoffice).

The reference scheduler assigns roles and EvenDivides the key range over
servers when nodes register. Here the "cluster table" is a
``jax.sharding.Mesh`` with axes (data, kv): built once, it fixes both the
worker sharding (data axis) and the server key ranges (kv axis — see
utils.keyrange for the same math)."""

from __future__ import annotations

import jax
from jax.sharding import Mesh


def make_mesh(
    data_shards: int,
    kv_shards: int,
    devices: list[jax.Device] | None = None,
) -> Mesh:
    devs = list(devices) if devices is not None else list(jax.devices())
    need = data_shards * kv_shards
    if need > len(devs):
        raise ValueError(
            f"mesh {data_shards}x{kv_shards} needs {need} devices, have {len(devs)}"
        )
    import numpy as np

    grid = np.array(devs[:need]).reshape(data_shards, kv_shards)
    return Mesh(grid, axis_names=("data", "kv"))
