"""SPMD pull/push: the reference's wire protocol re-expressed as collectives.

Reference analog, mapped one-to-one:

  Executor::Submit slicing a pulled key set across server ranges
    (src/system/executor.*, parallel_ordered_match)      -> masked local
    gather against this shard's contiguous range + ``psum`` over the "kv"
    axis (out-of-range rows contribute zero).
  Worker Push of per-minibatch gradients to the server group
    (src/parameter/shared_parameter.h kPush)             -> ``all_gather``
    of (keys, grads) over the "data" axis, then each kv shard applies every
    worker's push **sequentially** (a lax.scan), which reproduces the
    reference server's semantics of applying each worker's push as its own
    nonlinear updater step — NOT a pre-averaged BSP step.
  Server updater application (FTRL/AdaGrad/SGD entries)  -> exact additive
    deltas scattered with ``.at[].add`` (deterministic under padding).

State layout: every table is (num_keys, vdim) sharded over "kv" on axis 0;
num_keys must divide evenly by the kv axis size. Batches are per-data-shard
CSRBatches stacked on a leading axis and sharded over "data".
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jax import shard_map

from parameter_server_tpu.data.batch import CSRBatch
from parameter_server_tpu.kv.updaters import Updater
from parameter_server_tpu.ops.sparse import csr_grad, csr_logits, logistic_loss

State = dict[str, jax.Array]
Batch = dict[str, jax.Array]


def state_spec() -> P:
    return P("kv", None)


def batch_spec() -> P:
    return P("data", None)


def shard_state(state: State, mesh: Mesh) -> State:
    """Place a replicated/host state dict range-sharded over the kv axis."""
    sh = NamedSharding(mesh, state_spec())
    return {k: jax.device_put(v, sh) for k, v in state.items()}


def stack_fields(
    batches: list, fields: tuple[str, ...], mesh: Mesh | None = None
) -> Batch:
    """Stack the named attributes of D per-worker batches on a leading axis;
    with a mesh, place the result sharded over the "data" axis."""
    import numpy as np

    out = {f: np.stack([getattr(b, f) for b in batches]) for f in fields}
    if mesh is None:
        return {k: jnp.asarray(v) for k, v in out.items()}
    sh = NamedSharding(mesh, batch_spec())
    return {k: jax.device_put(v, sh) for k, v in out.items()}


def stack_batches(batches: list[CSRBatch], mesh: Mesh | None = None) -> Batch:
    """Stack D per-worker CSR batches; shard over "data"."""
    return stack_fields(
        batches,
        ("unique_keys", "local_ids", "row_ids", "values", "labels", "example_mask"),
        mesh,
    )


def _local_pull(
    updater: Updater, state_l: State, idx: jax.Array, shard_size: int
) -> jax.Array:
    """This shard's contribution to pulled weights for global ids ``idx``."""
    begin = lax.axis_index("kv") * shard_size
    local = idx - begin
    in_range = (local >= 0) & (local < shard_size)
    safe = jnp.where(in_range, local, 0)
    rows = {k: jnp.take(v, safe, axis=0) for k, v in state_l.items()}
    w = updater.weights(rows)
    return jnp.where(in_range[:, None], w, 0.0)


def _local_push(
    updater: Updater,
    state_l: State,
    all_idx: jax.Array,  # (D, U) pushes from every data shard
    all_grad: jax.Array,  # (D, U, vdim)
    shard_size: int,
) -> State:
    """Apply every worker's push to this kv shard, sequentially (ref: the
    server processes each worker's Push message as its own updater step)."""
    begin = lax.axis_index("kv") * shard_size

    def body(state_l: State, push: tuple[jax.Array, jax.Array]):
        idx, g = push
        local = idx - begin
        in_range = (local >= 0) & (local < shard_size)
        safe = jnp.where(in_range, local, 0)
        rows = {k: jnp.take(v, safe, axis=0) for k, v in state_l.items()}
        deltas = updater.delta(rows, g)
        mask = in_range[:, None].astype(g.dtype)
        new = {k: state_l[k].at[safe].add(mask * deltas[k]) for k in state_l}
        return new, None

    new_state, _ = lax.scan(body, state_l, (all_idx, all_grad))
    return new_state


def _shard_size(num_keys: int, kv_size: int) -> int:
    if num_keys % kv_size:
        raise ValueError(f"num_keys {num_keys} not divisible by kv axis {kv_size}")
    return num_keys // kv_size


def make_spmd_train_step(updater: Updater, mesh: Mesh, num_keys: int):
    """Build the jitted multi-device train step.

    step(state, batch) -> (state, {"loss_sum": scalar, "probs": (D, B)})
    """
    shard_size = _shard_size(num_keys, mesh.shape["kv"])

    def local_step(state_l: State, batch: Batch):
        b = {k: v[0] for k, v in batch.items()}  # this data shard's batch
        idx = b["unique_keys"]
        w_u = lax.psum(
            _local_pull(updater, state_l, idx, shard_size), "kv"
        )  # Pull: slice + merge (ref kv_vector match)
        logits = csr_logits(
            w_u, b["values"], b["local_ids"], b["row_ids"],
            num_rows=b["labels"].shape[0],
        )
        loss, err = logistic_loss(logits, b["labels"], b["example_mask"])
        g = csr_grad(
            err, b["values"], b["local_ids"], b["row_ids"], num_unique=idx.shape[0]
        )
        # Push: every data shard's (keys, grads) reach every kv shard.
        all_idx = lax.all_gather(idx, "data")  # (D, U)
        all_grad = lax.all_gather(g, "data")  # (D, U, vdim)
        new_state = _local_push(updater, state_l, all_idx, all_grad, shard_size)
        loss_sum = lax.psum(loss, "data")
        probs = jax.nn.sigmoid(logits)[None, :]  # (1, B) -> gathers to (D, B)
        return new_state, loss_sum, probs

    step = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_spec(), batch_spec()),
        out_specs=(state_spec(), P(), batch_spec()),
        check_vma=False,
    )

    @functools.partial(jax.jit, donate_argnums=0)
    def jitted(state: State, batch: Batch):
        new_state, loss_sum, probs = step(state, batch)
        return new_state, {"loss_sum": loss_sum, "probs": probs}

    return jitted


def make_spmd_predict_step(updater: Updater, mesh: Mesh, num_keys: int):
    shard_size = _shard_size(num_keys, mesh.shape["kv"])

    def local_predict(state_l: State, batch: Batch):
        b = {k: v[0] for k, v in batch.items()}
        w_u = lax.psum(
            _local_pull(updater, state_l, b["unique_keys"], shard_size), "kv"
        )
        logits = csr_logits(
            w_u, b["values"], b["local_ids"], b["row_ids"],
            num_rows=b["labels"].shape[0],
        )
        return jax.nn.sigmoid(logits)[None, :]

    step = shard_map(
        local_predict,
        mesh=mesh,
        in_specs=(state_spec(), batch_spec()),
        out_specs=batch_spec(),
        check_vma=False,
    )
    return jax.jit(step)
