"""SPMD pull/push: the reference's wire protocol re-expressed as collectives.

Reference analog, mapped one-to-one:

  Executor::Submit slicing a pulled key set across server ranges
    (src/system/executor.*, parallel_ordered_match)      -> masked local
    gather against this shard's contiguous range + ``psum`` over the "kv"
    axis (out-of-range rows contribute zero).
  Worker Push of per-minibatch gradients to the server group
    (src/parameter/shared_parameter.h kPush)             -> ``all_gather``
    of (keys, grads) over the "data" axis, then each kv shard applies every
    worker's push **sequentially** (a lax.scan), which reproduces the
    reference server's semantics of applying each worker's push as its own
    nonlinear updater step — NOT a pre-averaged BSP step.
  Server updater application (FTRL/AdaGrad/SGD entries)  -> exact additive
    deltas scattered with ``.at[].add`` (deterministic under padding).

State layout: every table is (num_keys, vdim) sharded over "kv" on axis 0.
``num_keys`` need not divide the kv axis size: tables are zero-padded up
to the next axis multiple (``padded_num_keys``) and the pad rows stay
exactly zero under the store's pad-row invariant (batch keys are always
below the real ``num_keys``, so no push ever touches them). Batches are
per-data-shard CSRBatches stacked on a leading axis and sharded over
"data".
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from parameter_server_tpu.utils.jaxcompat import shard_map

from parameter_server_tpu.data.batch import CSRBatch
from parameter_server_tpu.kv.updaters import Updater
from parameter_server_tpu.ops.sparse import csr_grad, csr_logits, logistic_loss

State = dict[str, jax.Array]
Batch = dict[str, jax.Array]


def state_spec() -> P:
    return P("kv", None)


def batch_spec() -> P:
    return P("data", None)


def shard_state(state: State, mesh: Mesh) -> State:
    """Place a replicated/host state dict range-sharded over the kv axis,
    zero-padding the tables up to the next kv-axis multiple first (the
    pad rows are inert — see ``kv.store.pad_state_rows``)."""
    from parameter_server_tpu.kv.store import pad_state_rows

    rows = next(iter(state.values())).shape[0]
    state = pad_state_rows(state, padded_num_keys(rows, mesh.shape["kv"]))
    sh = NamedSharding(mesh, state_spec())
    return {k: jax.device_put(v, sh) for k, v in state.items()}


def stack_fields(
    batches: list, fields: tuple[str, ...], mesh: Mesh | None = None
) -> Batch:
    """Stack the named attributes of D per-worker batches on a leading axis;
    with a mesh, place the result sharded over the "data" axis. Without a
    mesh the stacks stay host-side numpy — callers either feed them to jit
    directly or hand them to Runtime.globalize_batch (which must not pay a
    device round-trip first)."""
    import numpy as np

    out = {f: np.stack([getattr(b, f) for b in batches]) for f in fields}
    return out if mesh is None else place_stacked(out, mesh)


def place_stacked(stacked: dict, mesh: Mesh) -> dict:
    """Place already-stacked (D, ...) host arrays sharded over "data" —
    the one home for the data-axis placement spec (apps share it)."""
    sh = NamedSharding(mesh, batch_spec())
    return {k: jax.device_put(v, sh) for k, v in stacked.items()}


CSR_FULL_FIELDS = (
    "unique_keys", "local_ids", "row_ids", "values", "labels", "example_mask",
)
# Compact wire format: row structure rides as (B+1,) row_splits instead of
# (NNZ,) row_ids — ~40% fewer host->device bytes at typical densities (the
# usual bottleneck on PCIe/tunnel feeds); the device rebuilds row ids with
# one searchsorted (see _row_ids_of).
CSR_COMPACT_FIELDS = (
    "unique_keys", "local_ids", "row_splits", "values", "labels", "example_mask",
)


_F16_MAX = 65504.0  # largest finite float16


def stack_batches(
    batches: list[CSRBatch],
    mesh: Mesh | None = None,
    compact: bool = False,
    values_f16: bool = False,
) -> Batch:
    """Stack D per-worker CSR batches; shard over "data".

    values_f16 (the data.wire_values="f16" knob) halves the value bytes
    on the feed: values are clipped to the finite f16 range (a silent
    inf from an un-scaled count feature would NaN the loss and poison
    the optimizer state) and cast; the device casts back to f32
    (_values_of). One home for the encode so every feed path — train and
    eval — gets the same wire."""
    import numpy as np

    out = stack_fields(
        batches, CSR_COMPACT_FIELDS if compact else CSR_FULL_FIELDS, None
    )
    if values_f16:
        out["values"] = np.clip(out["values"], -_F16_MAX, _F16_MAX).astype(
            np.float16
        )
    return out if mesh is None else place_stacked(out, mesh)


def _row_ids_of(b: Batch) -> jax.Array:
    """Entry -> example-row ids for one shard's batch: passthrough for the
    full wire format, one searchsorted over (B+1,) row_splits for the
    compact one. Padded entries (value 0) clamp to the last row and stay
    inert under the masked loss/grad ops."""
    if "row_ids" in b:
        return b["row_ids"]
    nnz = b["values"].shape[0]
    num_rows = b["labels"].shape[0]
    e = jnp.arange(nnz, dtype=jnp.int32)
    r = jnp.searchsorted(b["row_splits"], e, side="right").astype(jnp.int32) - 1
    return jnp.clip(r, 0, num_rows - 1)


def _values_of(b: Batch) -> jax.Array:
    """Feature values in compute precision: f16-wire batches (the
    data.wire_values knob — half the value bytes on the feed) cast back
    to f32 on-device; f32 wires pass through."""
    v = b["values"]
    return v.astype(jnp.float32) if v.dtype != jnp.float32 else v


def _local_pull(
    updater: Updater, state_l: State, idx: jax.Array, shard_size: int
) -> jax.Array:
    """This shard's contribution to pulled weights for global ids ``idx``."""
    begin = lax.axis_index("kv") * shard_size
    local = idx - begin
    in_range = (local >= 0) & (local < shard_size)
    safe = jnp.where(in_range, local, 0)
    rows = {k: jnp.take(v, safe, axis=0) for k, v in state_l.items()}
    w = updater.weights(rows)
    return jnp.where(in_range[:, None], w, 0.0)


def _local_push(
    updater: Updater,
    state_l: State,
    all_idx: jax.Array,  # (D, U) pushes from every data shard
    all_grad: jax.Array,  # (D, U, vdim)
    shard_size: int,
) -> State:
    """Apply every worker's push to this kv shard, sequentially (ref: the
    server processes each worker's Push message as its own updater step)."""
    begin = lax.axis_index("kv") * shard_size

    def body(state_l: State, push: tuple[jax.Array, jax.Array]):
        idx, g = push
        local = idx - begin
        in_range = (local >= 0) & (local < shard_size)
        safe = jnp.where(in_range, local, 0)
        rows = {k: jnp.take(v, safe, axis=0) for k, v in state_l.items()}
        deltas = updater.delta(rows, g)
        mask = in_range[:, None].astype(g.dtype)
        new = {k: state_l[k].at[safe].add(mask * deltas[k]) for k in state_l}
        return new, None

    new_state, _ = lax.scan(body, state_l, (all_idx, all_grad))
    return new_state


def _local_push_aggregate(
    updater: Updater,
    state_l: State,
    idx: jax.Array,  # (U,) this data shard's unique keys
    grad: jax.Array,  # (U, vdim) this data shard's per-key grads
    shard_size: int,
) -> State:
    """Aggregate-then-update push (the BASELINE north star's
    "push ≡ reduce-scatter"): every data shard scatters its grads into a
    dense buffer covering ONLY this device's kv range, a single ``psum``
    over "data" pre-sums them, and the updater applies ONE step to the
    touched rows.

    vs ``_local_push``: O(1) updater applications instead of an O(D)
    serialized scan, and the wire moves 2·S rows (ring psum of the range
    slice) instead of D·U gathered rows — the win grows with data shards.

    Semantic difference (documented, opt-in): the reference server applies
    each worker's push as its own updater step; this mode applies the
    SUMMED gradient once. For linear deltas (plain SGD, lambda_l2=0) the
    two are exactly equal; for FTRL/AdaGrad this is standard synchronous
    minibatch aggregation (same fixed point, different trajectory).
    """
    begin = lax.axis_index("kv") * shard_size
    local = idx - begin
    in_range = (local >= 0) & (local < shard_size)
    safe = jnp.where(in_range, local, 0)
    mask = in_range[:, None].astype(grad.dtype)
    vdim = grad.shape[-1]
    g_slice = jnp.zeros((shard_size, vdim), grad.dtype).at[safe].add(mask * grad)
    touched = jnp.zeros((shard_size, 1), grad.dtype).at[safe].add(mask)
    # one collective pre-sums every worker's contribution to this range
    g_slice = lax.psum(g_slice, "data")
    touched = lax.psum(touched, "data")
    deltas = updater.delta(state_l, g_slice)
    hit = (touched > 0).astype(grad.dtype)
    return {k: state_l[k] + hit * deltas[k] for k in state_l}


def _local_push_quantized(
    updater: Updater,
    state_l: State,
    idx: jax.Array,  # (U,) this data shard's unique keys
    grad: jax.Array,  # (U, vdim)
    shard_size: int,
    push_seed: jax.Array,  # scalar int32, varies per step
    stream: int = 0,  # static sub-stream tag (multi-table apps: one per table)
) -> State:
    """Per-worker push with int8-quantized gradients on the wire (the
    reference's fixing_float filter re-expressed as a quantized
    COLLECTIVE, cf. EQuARX): each data shard quantizes its gradient
    symmetrically to int8 with one f32 scale and stochastic (unbiased)
    rounding; the all_gather then moves 1 byte per value instead of 4 —
    the payload that dominates cross-slice DCN traffic. Dequantization
    happens after the gather, so server semantics stay exactly
    ``_local_push`` (each worker's push is its own updater step).

    ``stream`` decorrelates the rounding noise between pushes that share
    one push_seed (Wide&Deep pushes two tables per microstep); 0 keeps
    the original key schedule, so single-table trajectories are stable."""
    key = jax.random.fold_in(
        jax.random.key(push_seed), lax.axis_index("data")
    )
    if stream:
        key = jax.random.fold_in(key, stream)
    scale = jnp.max(jnp.abs(grad)) / 127.0 + 1e-30
    t = grad / scale
    floor = jnp.floor(t)
    q = floor + (jax.random.uniform(key, grad.shape) < (t - floor))
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    # the wire: indices + int8 payload + one scale per worker
    all_idx = lax.all_gather(idx, "data")  # (D, U)
    all_q = lax.all_gather(q, "data")  # (D, U, vdim) int8
    all_scale = lax.all_gather(scale, "data")  # (D,)
    all_grad = all_q.astype(grad.dtype) * all_scale[:, None, None]
    return _local_push(updater, state_l, all_idx, all_grad, shard_size)


PUSH_MODES = ("per_worker", "aggregate", "quantized")


def padded_num_keys(num_keys: int, kv_size: int) -> int:
    """``num_keys`` rounded up to the next multiple of the kv axis size —
    the table rows the sharded tiers actually allocate. The rows past the
    real ``num_keys`` are pad rows: exactly zero and never touched (the
    data layer only emits keys below ``num_keys``), so arbitrary table
    sizes run on any mesh shape with no semantic change."""
    if num_keys < 1:
        raise ValueError(f"num_keys must be >= 1, got {num_keys}")
    return -(-num_keys // kv_size) * kv_size


def _shard_size(num_keys: int, kv_size: int) -> int:
    return padded_num_keys(num_keys, kv_size) // kv_size


def _wrap_stepper(step, push_mode: str):
    """Shared jit + push_seed contract for the single- and multi-step
    makers (one home for the quantized-seed guard): ``step`` is the
    shard_map'd program (state, batch, seed) -> (state, loss, ex, probs)."""

    @functools.partial(jax.jit, donate_argnums=0)
    def _jitted(state: State, batch: Batch, push_seed):
        new_state, loss, ex, probs = step(state, batch, jnp.int32(push_seed))
        return new_state, {"loss_sum": loss, "examples": ex, "probs": probs}

    def stepper(state: State, batch: Batch, push_seed=None):
        if push_seed is None:
            if push_mode == "quantized":
                # a silently-defaulted seed would reuse the same PRNG key
                # every step, correlating the stochastic rounding noise
                # instead of averaging it out
                raise ValueError(
                    "quantized push mode requires a per-step push_seed: "
                    "call step(state, batch, step_index)"
                )
            push_seed = 0
        return _jitted(state, batch, push_seed)

    return stepper


def _microstep(
    updater: Updater,
    state_l: State,
    b: Batch,  # one data shard's un-stacked batch fields
    shard_size: int,
    push_mode: str,
    push_seed: jax.Array,
):
    """One parameter-server step on this device: pull -> CSR grad -> push.
    Shared verbatim by the single-step and scanned multi-step programs so
    the wire semantics cannot diverge between them."""
    idx = b["unique_keys"]
    row_ids = _row_ids_of(b)
    values = _values_of(b)
    w_u = lax.psum(
        _local_pull(updater, state_l, idx, shard_size), "kv"
    )  # Pull: slice + merge (ref kv_vector match)
    logits = csr_logits(
        w_u, values, b["local_ids"], row_ids,
        num_rows=b["labels"].shape[0],
    )
    loss, err = logistic_loss(logits, b["labels"], b["example_mask"])
    g = csr_grad(
        err, values, b["local_ids"], row_ids, num_unique=idx.shape[0]
    )
    if push_mode == "aggregate":
        new_state = _local_push_aggregate(updater, state_l, idx, g, shard_size)
    elif push_mode == "quantized":
        new_state = _local_push_quantized(
            updater, state_l, idx, g, shard_size, push_seed
        )
    else:
        # Push: every data shard's (keys, grads) reach every kv shard.
        all_idx = lax.all_gather(idx, "data")  # (D, U)
        all_grad = lax.all_gather(g, "data")  # (D, U, vdim)
        new_state = _local_push(updater, state_l, all_idx, all_grad, shard_size)
    loss_sum = lax.psum(loss, "data")
    # pod-wide real-example count: the host-side termination signal
    # (a drained host keeps feeding empty batches; every host stops
    # deterministically after retiring a step with examples == 0 —
    # this rides async dispatch instead of a blocking host barrier)
    examples = lax.psum(jnp.sum(b["example_mask"]), "data")
    probs = jax.nn.sigmoid(logits)
    return new_state, loss_sum, examples, probs


def make_spmd_train_step(
    updater: Updater, mesh: Mesh, num_keys: int, push_mode: str = "per_worker"
):
    """Build the jitted multi-device train step.

    step(state, batch) -> (state, out) with out keys:
      "loss_sum" — scalar, psum over data
      "examples" — scalar pod-wide real-example count (the host-side
          termination signal; see PodTrainer's drained contract)
      "probs"    — (D, B) per-shard probabilities

    push_mode:
      "per_worker" — faithful reference semantics: each data shard's push is
          its own server updater step (all_gather + sequential scan).
      "aggregate"  — pre-sum per-key grads across data shards with one psum,
          apply one updater step (see ``_local_push_aggregate``; exactly
          equal for linear SGD, standard sync aggregation otherwise).
      "quantized"  — per_worker semantics with int8 gradients on the wire
          (see ``_local_push_quantized``; the fixing_float filter as a
          quantized collective for DCN-limited pods).
    """
    if push_mode not in PUSH_MODES:
        raise ValueError(f"unknown push_mode {push_mode!r}; known: {PUSH_MODES}")
    shard_size = _shard_size(num_keys, mesh.shape["kv"])

    def local_step(state_l: State, batch: Batch, push_seed: jax.Array):
        b = {k: v[0] for k, v in batch.items()}  # this data shard's batch
        new_state, loss_sum, examples, probs = _microstep(
            updater, state_l, b, shard_size, push_mode, push_seed
        )
        return new_state, loss_sum, examples, probs[None, :]  # -> (D, B)

    step = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_spec(), batch_spec(), P()),
        out_specs=(state_spec(), P(), P(), batch_spec()),
        check_vma=False,
    )
    return _wrap_stepper(step, push_mode)


def make_spmd_train_multistep(
    updater: Updater, mesh: Mesh, num_keys: int, push_mode: str = "per_worker"
):
    """K parameter-server steps per device call: ``lax.scan`` over a
    leading microstep axis inside ONE jitted shard_map program.

    Why: on a tunneled or dispatch-bound host, per-step host->device
    round trips (transfer + dispatch + retirement sync) put a hard floor
    under examples/sec no matter how fast the chip is. Scanning K
    microsteps amortizes that floor K-fold: one transfer of K stacked
    batches in, one device program, one retirement out. The TPU idiom for
    the reference's bounded-delay pipelining of many small Push/Pull
    tasks (SURVEY §2.9 SSP): the steps stay SEQUENTIAL — microstep i+1
    pulls weights that include microstep i's push, exactly as if
    dispatched one by one — so the math is the single-step trajectory,
    not a K-times-larger batch.

    batch fields are stacked (D, K, ...): data shard leading (sharded),
    microstep second (scanned). step(state, batch, push_seed) ->
    (state, out) with out keys:
      "loss_sum" — (K,) per-microstep pod-wide loss sums
      "examples" — (K,) per-microstep pod-wide real-example counts (the
          termination contract checks the LAST entry: empties only ever
          trail real batches within a group)
      "probs"    — (D, K, B) per-shard, per-microstep probabilities
    """
    if push_mode not in PUSH_MODES:
        raise ValueError(f"unknown push_mode {push_mode!r}; known: {PUSH_MODES}")
    shard_size = _shard_size(num_keys, mesh.shape["kv"])

    def local_step(state_l: State, batch: Batch, push_seed: jax.Array):
        b = {k: v[0] for k, v in batch.items()}  # this shard's (K, ...) group
        n_micro = b["labels"].shape[0]

        def body(st: State, micro):
            mb, i = micro
            # quantized mode: a distinct PRNG key per microstep (the
            # same per-step-seed contract as single-step dispatch)
            new_st, loss, ex, probs = _microstep(
                updater, st, mb, shard_size, push_mode, push_seed + i
            )
            return new_st, (loss, ex, probs)

        new_state, (losses, exs, probs) = lax.scan(
            body, state_l, (b, jnp.arange(n_micro, dtype=jnp.int32))
        )
        return new_state, losses, exs, probs[None]  # -> (D, K, B)

    step = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_spec(), batch_spec(), P()),
        out_specs=(state_spec(), P(), P(), batch_spec()),
        check_vma=False,
    )
    return _wrap_stepper(step, push_mode)


def stack_step_groups(stacked_items: list[Batch]) -> Batch:
    """Stack K per-step stacked dicts — each (D, ...) — into one (D, K, ...)
    multistep group. Bucketed items are first zero-padded to the group max
    on their variable (trailing) axis; buckets are powers of two, so the
    set of group shapes (and compiled programs) stays small."""
    import numpy as np

    from parameter_server_tpu.data.batch import zero_extend

    targets = {
        f: max(d[f].shape[-1] for d in stacked_items)
        for f in stacked_items[0]
    }
    return {
        f: np.stack(
            [zero_extend(d[f], targets[f], axis=-1) for d in stacked_items],
            axis=1,
        )
        for f in stacked_items[0]
    }


def make_spmd_predict_step(updater: Updater, mesh: Mesh, num_keys: int):
    shard_size = _shard_size(num_keys, mesh.shape["kv"])

    def local_predict(state_l: State, batch: Batch):
        b = {k: v[0] for k, v in batch.items()}
        w_u = lax.psum(
            _local_pull(updater, state_l, b["unique_keys"], shard_size), "kv"
        )
        logits = csr_logits(
            w_u, _values_of(b), b["local_ids"], _row_ids_of(b),
            num_rows=b["labels"].shape[0],
        )
        return jax.nn.sigmoid(logits)[None, :]

    step = shard_map(
        local_predict,
        mesh=mesh,
        in_specs=(state_spec(), batch_spec()),
        out_specs=batch_spec(),
        check_vma=False,
    )
    return jax.jit(step)
