"""Multi-host runtime bootstrap.

Reference analog: process startup — main.cc flags -> Postoffice::Run ->
scheduler assigns node ids + key ranges, nodes connect (src/system/
postoffice.*, van.*) — plus the mpirun/hostfile launchers (script/). On a
TPU pod the cluster manager starts one identical process per host; this
module is what those processes call first:

    rt = runtime.init(coordinator_addr, num_processes, process_id)
    trainer = PodTrainer(cfg, runtime=rt)
    trainer.train_files(all_files)  # trainer shards the list per host

``init`` wires ``jax.distributed.initialize`` (the control plane the
reference's scheduler registry collapses into), builds the global
(data, kv) mesh from per-process devices, and hands out the host-local
views of it. Mesh layout contract: the **kv axis lives within each
process** and the **data axis spans processes** — so every host feeds
only its own data shards from local files (the reference's
worker-owns-its-shard design) and every host holds a full replica of the
range-sharded server state across its local devices (which makes
checkpoint writes shardable by host and evaluation host-local).

Simulated hosts for tests (SURVEY §4(b)): run N processes with
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=K``
and gloo CPU collectives — exercised by tests/test_multihost.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np


@dataclass(frozen=True)
class Runtime:
    """Handle on the initialized multi-host run (or a single-host run when
    ``process_count == 1`` — every helper degrades to the local path)."""

    mesh: Any  # jax.sharding.Mesh over (data, kv)
    process_index: int
    process_count: int
    data_shards: int  # global data axis size
    kv_shards: int
    local_data_shards: int  # data rows owned by this process
    # cp_allmax's deferred-deletion slot (mutable on the frozen handle):
    # holds the one previous tag whose published max is deleted on the
    # next call — see cp_allmax's cleanup note
    _cp_state: dict = field(default_factory=dict, repr=False, compare=False)

    # -- input sharding ---------------------------------------------------

    def shard_files(self, files: list[str]) -> list[str]:
        """This host's input file shard (ref: the scheduler's WorkloadPool
        hands file shards to workers; across hosts the split is static)."""
        return list(files)[self.process_index :: self.process_count]

    # -- host-local <-> global arrays ------------------------------------

    def globalize_batch(self, arrays: dict[str, np.ndarray]) -> dict:
        """Lift this host's stacked (local_data_shards, ...) batch arrays
        into global arrays sharded over the full data axis."""
        if self.process_count == 1:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            sh = NamedSharding(self.mesh, P("data", None))
            return {k: jax.device_put(v, sh) for k, v in arrays.items()}
        from jax.experimental import multihost_utils
        from jax.sharding import PartitionSpec as P

        return {
            k: multihost_utils.host_local_array_to_global_array(
                np.asarray(v), self.mesh, P("data", None)
            )
            for k, v in arrays.items()
        }

    def localize_data(self, arr) -> np.ndarray:
        """This host's (local_data_shards, ...) slice of a P("data", ...)
        output (e.g. per-shard probabilities)."""
        if self.process_count == 1:
            return np.asarray(arr)
        from jax.experimental import multihost_utils
        from jax.sharding import PartitionSpec as P

        return np.asarray(
            multihost_utils.global_array_to_host_local_array(
                arr, self.mesh, P("data", None)
            )
        )

    # -- state ------------------------------------------------------------

    def init_state(self, init_fn) -> dict:
        """Build the kv-sharded global state: each device materializes its
        slice (no host-side full copy, no cross-host transfer)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(self.mesh, P("kv", None))
        return jax.jit(init_fn, out_shardings=sh)()

    def state_to_host(self, state: dict) -> dict[str, np.ndarray]:
        """Assemble the FULL state on this host from its addressable
        shards. Valid under the layout contract (kv within process): every
        host holds a complete replica across its devices."""
        out = {}
        for name, arr in state.items():
            pieces: dict[int, np.ndarray] = {}
            for s in arr.addressable_shards:
                start = s.index[0].start or 0
                pieces[start] = np.asarray(s.data)
            out[name] = np.concatenate(
                [pieces[k] for k in sorted(pieces)], axis=0
            )
        return out

    def state_from_host(self, host_state: dict[str, np.ndarray]) -> dict:
        """Inverse of ``state_to_host``: place a full host-local state dict
        back onto the mesh (each device takes its kv slice)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(self.mesh, P("kv", None))
        if self.process_count == 1:
            return {k: jax.device_put(v, sh) for k, v in host_state.items()}
        from jax.experimental import multihost_utils
        from jax.sharding import PartitionSpec as P

        # kv is within-process, so the host-local array already has global
        # shape; multihost placement just needs the global-array wrapper
        return {
            k: multihost_utils.host_local_array_to_global_array(
                v, self.mesh, P("kv", None)
            )
            for k, v in host_state.items()
        }

    # -- checkpoint -------------------------------------------------------

    def save_checkpoint(
        self, ckpt_dir, state: dict, meta: dict | None = None
    ) -> None:
        """Per-host sharded write (ref: each server dumps its own key
        range): host p writes key rows [p, p+P) / P of every table from its
        local replica; the manifest comes from host 0."""
        from parameter_server_tpu.utils.checkpoint import save_checkpoint

        host = self.state_to_host(state)
        rows = next(iter(host.values())).shape[0]
        if rows % self.process_count:
            raise ValueError(
                f"num_keys {rows} not divisible by {self.process_count} hosts"
            )
        per = rows // self.process_count
        lo = self.process_index * per
        save_checkpoint(
            ckpt_dir,
            {k: v[lo : lo + per] for k, v in host.items()},
            meta=meta,
            shard_id=self.process_index,
            num_shards=self.process_count,
        )

    def load_checkpoint(self, ckpt_dir) -> tuple[dict, dict]:
        """Each host reads all shards (contiguous key ranges), assembles its
        full replica, and re-places it on the mesh."""
        from parameter_server_tpu.utils.checkpoint import load_checkpoint

        host_state, meta = load_checkpoint(ckpt_dir)
        return self.state_from_host(host_state), meta

    def cp_allmax(
        self, tag: str, values: tuple[int, ...], timeout_ms: int = 600_000
    ) -> tuple[int, ...] | None:
        """Control-plane elementwise max across processes via the
        coordination-service KV store — NO device collective, so the
        dispatch thread never syncs to the device stream and async
        run-ahead (SSP max_delay) survives. This is the bucket-agreement
        fast path. Single-process runtimes short-circuit to the local
        values; ``None`` means a MULTI-process runtime has no distributed
        client wired (built without jax.distributed) and the caller
        should fall back to a device allgather.

        ``tag`` must be unique per reduction pod-wide and issued in the
        same order on every process (the trainer uses "<epoch-gen>/<step>").
        Designated-reducer shape: every process posts its values; process
        0 reads all P posts and publishes the max; followers do ONE
        blocking get on the published key — O(1) RPCs per follower per
        step, so the control-plane cost does not grow with the pod on the
        dispatch critical path (process 0 pays O(P), off-device).

        Cleanup (bounded across arbitrarily many calls/epochs/trainers):
        a follower deletes its own post right after its get succeeds —
        the published max existing proves process 0 already read every
        post of this tag. Process 0 deletes the PREVIOUS call's max after
        publishing the current one: its posts being all in proves every
        process completed the previous call's get (calls are issued in
        identical order per process). Steady-state KV footprint is
        therefore exactly one `max` key; only the final call's max of a
        Runtime's lifetime leaks (O(1), reclaimed when the coordinator
        exits)."""
        if self.process_count == 1:
            return tuple(int(v) for v in values)
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            return None
        me = self.process_index
        enc = ",".join(str(int(v)) for v in values)
        if me == 0:
            out = [int(v) for v in values]
            for p in range(1, self.process_count):
                got = client.blocking_key_value_get(
                    f"psbkt/{tag}/{p}", timeout_ms
                )
                for i, v in enumerate(got.split(",")):
                    out[i] = max(out[i], int(v))
            client.key_value_set(
                f"psbkt/{tag}/max", ",".join(str(v) for v in out)
            )
            prev = self._cp_state.get("prev_tag")
            if prev is not None:
                client.key_value_delete(f"psbkt/{prev}/max")
            self._cp_state["prev_tag"] = tag
            return tuple(out)
        client.key_value_set(f"psbkt/{tag}/{me}", enc)
        got = client.blocking_key_value_get(f"psbkt/{tag}/max", timeout_ms)
        client.key_value_delete(f"psbkt/{tag}/{me}")
        return tuple(int(v) for v in got.split(","))

    def barrier(self, name: str = "") -> None:
        if self.process_count == 1:
            return
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name or "ps_runtime_barrier")


def init(
    coordinator_addr: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    kv_shards: int = 1,
    data_shards: int | None = None,
    cfg=None,
) -> Runtime:
    """Bootstrap this process into the pod and build the global mesh.

    Single-host: call with no coordinator (or num_processes=1). Multi-host:
    every process calls with the same coordinator address and its own
    process_id — the TPU analog of `-scheduler ip:port -my_node ...`.

    cfg: a PSConfig — when given, the mesh shape comes from
    ``cfg.parallel`` and the explicit kv_shards/data_shards kwargs must
    not be used (ONE source of truth; PodTrainer re-checks its cfg
    against the runtime mesh and fails loudly on mismatch).
    """
    import jax

    if cfg is not None:
        if kv_shards != 1 or data_shards is not None:
            raise ValueError(
                "pass EITHER cfg (mesh shape from cfg.parallel) OR explicit "
                "kv_shards/data_shards — not both"
            )
        kv_shards = cfg.parallel.kv_shards
        data_shards = cfg.parallel.data_shards

    if coordinator_addr is None and (num_processes or 1) > 1:
        # the mirror of the guard below: N processes launched without a
        # coordinator would each run the FULL workload independently
        raise ValueError(
            f"num_processes={num_processes} requires a coordinator address"
        )
    if coordinator_addr is not None:
        if num_processes is None or num_processes < 2:
            # a forgotten --num_processes would otherwise yield N silent
            # INDEPENDENT runs clobbering each other's outputs
            raise ValueError(
                "a coordinator address requires num_processes >= 2 "
                f"(got {num_processes!r})"
            )
        # env check only — probing jax.default_backend() here would
        # initialize the backend BEFORE distributed init, hiding the pod
        if _cpu_platform_requested():
            # simulated hosts: CPU collectives ride gloo
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            coordinator_address=coordinator_addr,
            num_processes=num_processes,
            process_id=process_id,
        )
    procs = jax.process_count()
    local = jax.local_device_count()
    if local % kv_shards:
        raise ValueError(
            f"kv_shards {kv_shards} must divide local device count {local}: "
            "the kv axis must live within each process (layout contract)"
        )
    rows_per_proc = local // kv_shards
    max_data = procs * rows_per_proc
    data = data_shards if data_shards is not None else max_data
    if data > max_data or data % procs:
        raise ValueError(
            f"data_shards {data} must be a multiple of {procs} processes "
            f"and at most {max_data}"
        )
    # process-major device order keeps each data row on exactly one
    # process; when using fewer rows than available, take the same number
    # of rows from EVERY process (never starve a process of mesh devices)
    rows_used = data // procs
    blocks = np.array(jax.devices()).reshape(procs, rows_per_proc, kv_shards)
    for p in range(procs):
        owners = {d.process_index for d in blocks[p].flatten()}
        if owners != {p}:
            # the whole module's layout contract (kv within process, data
            # across processes) leans on process-contiguous device order;
            # violating it would silently truncate state_to_host replicas
            raise RuntimeError(
                "jax.devices() is not process-contiguous: block for "
                f"process {p} spans processes {sorted(owners)}; cannot "
                "honor the mesh layout contract"
            )
    grid = blocks[:, :rows_used, :].reshape(data, kv_shards)
    from jax.sharding import Mesh

    mesh = Mesh(grid, axis_names=("data", "kv"))
    return Runtime(
        mesh=mesh,
        process_index=jax.process_index(),
        process_count=procs,
        data_shards=data,
        kv_shards=kv_shards,
        local_data_shards=data // procs,
    )


def _cpu_platform_requested() -> bool:
    import os

    return os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"
