"""Bounded-staleness (SSP) clock — the consistency engine.

Reference analog: src/system/executor.* — every Task carries a ``wait_time``
dependency; the worker's Executor blocks submission of step t until the
dependency (typically t - max_delay) has completed, yielding the tunable
consistency spectrum: sequential/BSP (tau=0), bounded delay (tau>0),
eventual/async (tau=inf) (ref: the OSDI'14 dependency model and the
``max_delay`` knob of the SGD configs).

On a TPU pod, collectives inside one program are synchronous, so per-step
asynchrony moves UP a level: the host pipelines *dispatch* of jitted steps
and this clock bounds how far any worker's dispatched step may run ahead of
the slowest worker's completed step. JAX's async dispatch gives the overlap;
the clock gives the bound."""

from __future__ import annotations

import threading
import time
from collections import deque
from collections.abc import Callable
from typing import Any

from parameter_server_tpu.utils import flightrec
from parameter_server_tpu.utils.metrics import observe_scalar, wire_counters


class DispatchWindow:
    """The host-side bounded async-dispatch window every trainer shares
    (the single home of the gate arithmetic — PodTrainer, the in-memory
    word2vec epoch, and the streaming word2vec path all retire through
    here, so the wait_time semantics can't silently diverge).

    Protocol, for step t about to be dispatched:
        window.gate(t)          # retire every entry <= t - max_delay - 1
        ... dispatch step t ...
        window.add(t, entry)
    and at a sync point: window.drain().

    ``retire(step, entry)`` is the caller's completion hook (it may block
    on device results — that block IS the SSP bound taking effect).
    """

    def __init__(self, max_delay: int, retire: Callable[[int, Any], None]):
        self.max_delay = max_delay
        self._retire = retire
        self._q: deque[tuple[int, Any]] = deque()
        self.max_inflight = 0  # observability: peak run-ahead reached

    def gate(self, step: int) -> None:
        target = step - self.max_delay - 1
        while self._q and self._q[0][0] <= target:
            self._retire(*self._q.popleft())

    def add(self, step: int, entry: Any) -> None:
        self._q.append((step, entry))
        self.max_inflight = max(self.max_inflight, len(self._q))

    def drain(self) -> None:
        while self._q:
            self._retire(*self._q.popleft())

    def wait_all(self) -> None:
        """The full sync point: retire EVERY in-flight entry (alias of
        ``drain`` — named for the trainer/worker call sites where the
        intent is a barrier on outstanding async work, not bookkeeping)."""
        self.drain()

    def __len__(self) -> int:
        return len(self._q)


class PushWindow:
    """Bounded window of in-flight push *futures* — the wire tier's sibling
    of :class:`DispatchWindow`. The worker loop issues one step's fan-out
    of async pushes (one future per shard server), then:

        window.gate()            # retire done heads; block over the bound
        ... issue step t's pushes ...
        window.add(t, futures)
    and at a sync point: window.wait_all().

    ``retire(step)`` fires exactly once per step, AFTER every one of its
    pushes completed (the worker hangs its ``ssp_finish`` there, so the
    SSP clock's bounded-delay contract holds with a pipelined wire:
    a step only counts as finished when its pushes are actually applied).
    ``max_inflight`` bounds whole steps riding the wire; blocking on the
    oldest step's futures IS the bound taking effect."""

    def __init__(self, max_inflight: int, retire: Callable[[int], None]):
        self.max_inflight = max(0, max_inflight)
        self._retire = retire
        self._q: deque[tuple[int, list]] = deque()
        self.max_inflight_seen = 0  # observability: peak step depth reached

    def gate(self) -> None:
        """Retire every finished head step, then keep retiring (blocking
        on unfinished pushes) until at most ``max_inflight`` steps remain
        in flight."""
        while self._q and (
            len(self._q) > self.max_inflight
            or all(f.done() for f in self._q[0][1])
        ):
            self._retire_head()

    def add(self, step: int, futures: list) -> None:
        self._q.append((step, list(futures)))
        self.max_inflight_seen = max(self.max_inflight_seen, len(self._q))

    def wait_all(self) -> None:
        """Full sync point: block until every in-flight push completed and
        every step retired (surfacing any push error)."""
        while self._q:
            self._retire_head()

    def _retire_head(self) -> None:
        step, futs = self._q.popleft()
        for f in futs:
            f.result()  # blocks; surfaces push errors to the caller
        self._retire(step)

    def __len__(self) -> int:
        return len(self._q)


class SSPClock:
    """Host-side bounded-delay clock over ``num_workers`` logical workers.

    Protocol per worker w at step t:
        clock.wait(w, t)    # blocks until min_finished >= t - max_delay
        ... issue step t ...
        clock.finish(w, t)  # marks w's step t complete

    max_delay < 0 means fully asynchronous (never block) — the reference's
    "eventual" consistency.
    """

    def __init__(self, num_workers: int, max_delay: int):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.num_workers = num_workers
        self.max_delay = max_delay
        self._finished = [-1] * num_workers  # highest finished step per worker
        # per-worker blocked-time accounting (telemetry: "where did this
        # step's 40 ms go" — the SSP gate is one of the places)
        self._blocked_s = [0.0] * num_workers
        self._blocked_n = [0] * num_workers
        # live-ops counter bookkeeping: ssp_blocked_ms is an int counter
        # but individual waits are often sub-millisecond — flooring per
        # event would systematically book 0 and silence the shipped
        # ssp_blocked_ms SLO rule. Book the whole-ms difference against
        # the running float total instead (cumulative error < 1 ms).
        self._blocked_ms_booked = 0
        # watchdog feed: workers currently parked on the gate, and a
        # movement counter every finish/retire advances — "busy with no
        # progress" is exactly a wedged clock
        self._waiters = 0
        self._moves = 0
        self._cv = threading.Condition()

    def _min_finished(self) -> int:
        return min(self._finished)

    def ready(self, worker: int, step: int) -> bool:
        """Non-blocking: may ``worker`` start ``step`` now?"""
        if self.max_delay < 0:
            return True
        with self._cv:
            return self._min_finished() >= step - self.max_delay - 1

    def wait(self, worker: int, step: int, timeout: float | None = None) -> bool:
        """Block until ``worker`` may start ``step``. Returns False on timeout.

        The gate: every worker must have finished step ``step - tau - 1``
        (so with tau=0 a worker can be at most 1 step ahead of the slowest —
        BSP up to pipelining, exactly the reference's wait_time semantics).
        """
        if self.max_delay < 0:
            return True
        target = step - self.max_delay - 1
        with self._cv:
            mf = self._min_finished()
            if mf >= target:
                # gate already open: no blocked time to book — but the
                # REALIZED staleness of this pass still gets recorded
                # (freshness plane, ISSUE 17): the bound only caps the
                # lag; how much of the allowance workers actually
                # consume is the distribution `cli ranges`/the
                # ssp_lag_clocks SLO read, and the un-blocked passes
                # are most of it
                self._observe_lag(step, mf)
                return True
            t0 = time.perf_counter()
            self._waiters += 1
            try:
                ok = self._cv.wait_for(
                    lambda: self._min_finished() >= target, timeout=timeout
                )
            finally:
                self._waiters -= 1
            mf = self._min_finished()
            blocked = time.perf_counter() - t0
            self._blocked_s[worker] += blocked
            self._blocked_n[worker] += 1
            whole_ms = (
                int(sum(self._blocked_s) * 1e3) - self._blocked_ms_booked
            )
            self._blocked_ms_booked += whole_ms
        if ok:
            self._observe_lag(step, mf)
        # live-ops signal (ISSUE 13): blocked time as a counter, so the
        # coordinator's time-series ring exposes a cluster-visible
        # "ms blocked per second" rate the [slo] engine alerts on
        if whole_ms > 0:
            wire_counters.inc("ssp_blocked_ms", whole_ms)
        flightrec.record(
            "ssp.wait", worker=worker, step=step,
            blocked_ms=round(blocked * 1e3, 3), granted=ok,
        )
        return ok

    def _observe_lag(self, step: int, min_finished: int) -> None:
        """Record the realized clock lag of one GRANTED gate pass: how
        many steps ahead of the slowest finished worker this step runs
        (0 = lockstep; ``max_delay`` = the whole allowance consumed).
        Count-valued series (``.n``): rides the telemetry plane raw, so
        ``p99(ssp.lag_clocks.n)`` is directly comparable to the
        configured bound — enforced vs realized staleness on one
        chart."""
        observe_scalar(
            "ssp.lag_clocks.n", max(step - 1 - min_finished, 0)
        )

    def finish(self, worker: int, step: int) -> None:
        with self._cv:
            if step > self._finished[worker]:
                self._finished[worker] = step
                self._moves += 1
                self._cv.notify_all()
        flightrec.record(
            "ssp.finish" if step < self.RETIRED else "ssp.retire",
            worker=worker, step=min(step, self.RETIRED),
        )

    def stall_probe(self) -> tuple[bool, int]:
        """Watchdog probe: busy while any worker is parked on the gate;
        progress is the clock's movement counter — a wedged clock is
        parked workers with no movement."""
        with self._cv:
            return self._waiters > 0, self._moves

    RETIRED = 1 << 60

    def retire(self, worker: int) -> None:
        """Mark ``worker`` done forever (out of data, or declared dead by
        the recovery sweep): it no longer gates the others (ref: a finished
        worker stops issuing dependencies). Idempotent, and a late
        ``finish`` from a falsely-declared-dead worker is absorbed by the
        monotonic max in ``finish`` — replay-safe both ways."""
        self.finish(worker, self.RETIRED)

    def is_retired(self, worker: int) -> bool:
        with self._cv:
            return self._finished[worker] >= self.RETIRED

    def progress(self) -> dict[str, Any]:
        with self._cv:
            return {
                "min_finished": self._min_finished(),
                "max_finished": max(self._finished),
                # which clocks recovery/drain released — the observable
                # trace of dead-node handling
                "retired": [
                    w for w, f in enumerate(self._finished) if f >= self.RETIRED
                ],
                # cumulative seconds (and waits) each worker spent parked
                # on the gate — the per-worker SSP-wait telemetry
                "blocked_s": [round(s, 6) for s in self._blocked_s],
                "blocked_n": list(self._blocked_n),
            }

    def state_dict(self) -> dict:
        with self._cv:
            return {"finished": list(self._finished), "max_delay": self.max_delay}

    def load_state_dict(self, d: dict) -> None:
        with self._cv:
            self._finished = list(d["finished"])
            self.max_delay = d["max_delay"]
            # blocked-time telemetry is per-process, not model state:
            # restart it with the restored worker count (the counter
            # bookkeeping restarts with it, or whole-ms deltas would go
            # negative against the zeroed totals and stall the counter)
            self._blocked_s = [0.0] * len(self._finished)
            self._blocked_n = [0] * len(self._finished)
            self._blocked_ms_booked = 0
            self._cv.notify_all()
