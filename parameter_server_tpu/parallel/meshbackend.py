"""In-mesh GSPMD KV backend: push/pull as collectives over the kv axis.

The SNIPPETS north star made concrete: when "workers" and "servers"
share one JAX process mesh, the parameter server IS a NamedSharding-
sharded ``(num_keys, vdim)`` state table over the ``kv`` axis of a
``parallel/mesh.py`` mesh — no sockets, no serialization, no apply
queue. The wire protocol maps onto collectives:

  Pull   -> each kv shard's masked local gather of its contiguous range
            + ``psum`` over "kv" (out-of-range rows contribute zero) —
            the reference's parallel_ordered_match as an ICI collective.
  Push   -> ONE sharded jitted update (the batched apply engine's
            single dispatch re-expressed per "Automatic Cross-Replica
            Sharding of Weight Update", arXiv 2004.13336) in the true
            reduce-scatter shape: the HOST slices the sorted global
            keys into per-shard contiguous segments (the wire tier's
            range fan-out, re-aimed at mesh shards), pads them to one
            pow2 bucket, and ships a ``("kv", bucket)``-sharded payload
            — each shard RECEIVES only its own segment and computes the
            updater delta on ~U/kv rows, not a masked copy of all U
            (which costs kv× redundant flops and kv× replicated
            transfer, and is why a naive replicated push stops scaling
            exactly where big pushes should win).
  quant  -> the PR-6 per-segment int8/int16 codec FUSED into that
            collective (EQuARX, arXiv 2506.17615): the gradient is
            quantized with stochastic rounding BEFORE it crosses the
            host->mesh boundary (the payload that moves is 1-2 bytes
            per coordinate + one f32 scale per segment) and dequantized
            inside the sharded update after the exchange. The client
            error-feedback residual is preserved exactly as on the
            socket tier — folded into the next push of the same keys
            exactly once per logical push — so the telescoping identity
            (applied + residual == sum of true gradients) still holds
            and the int8 win survives the transport change.
  SSP    -> stays a host-side barrier: ``flush()`` blocks on the state
            buffers; JAX async dispatch is the in-flight push window.

Tables are padded up to the kv-axis multiple (``spmd.padded_num_keys``;
pad rows stay exactly zero), and host-side key sets are padded to
power-of-two buckets so the compiled program set stays small (the
``bucket_nnz`` idiom applied to the client data plane).

Not thread-safe for concurrent pushes (one logical trainer owns the
table, like ``KVStore``); the quantization residual is still
lock-guarded and registered with the PS_RACE_WITNESS lockset witness so
a future multi-threaded caller is caught, not corrupted.
"""

from __future__ import annotations

import itertools
import threading
from concurrent.futures import Future
from typing import Any

import numpy as np

from parameter_server_tpu.parallel.backend import PSBackend
from parameter_server_tpu.utils import flightrec
from parameter_server_tpu.utils.metrics import race_track, wire_counters

#: key dtype on the host->mesh boundary (int32 halves the index bytes;
#: the table-row bound is checked at construction)
_MAX_ROWS = 1 << 31


class MeshBackend(PSBackend):
    """One sharded state table + three jitted programs (pull, f32 push,
    quantized push); pulls bucket by padded key-set size, pushes by the
    pow2 per-shard segment bucket of the sharded payload."""

    def __init__(
        self,
        updater,
        num_keys: int,
        vdim: int = 1,
        mesh=None,
        kv_shards: int | None = None,
        quant: str = "off",
        quant_seg: int = 256,
    ):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from parameter_server_tpu.parallel.mesh import make_mesh
        from parameter_server_tpu.parallel.spmd import padded_num_keys

        if quant not in ("off", "int8", "int16"):
            raise ValueError(
                f"mesh quant must be off|int8|int16, got {quant!r}"
            )
        if mesh is None:
            mesh = make_mesh(1, kv_shards or len(jax.devices()))
        if "kv" not in mesh.axis_names:
            raise ValueError(f"mesh {mesh.axis_names} has no 'kv' axis")
        self.mesh = mesh
        self.updater = updater
        self.num_keys = int(num_keys)
        self.vdim = int(vdim)
        kv = mesh.shape["kv"]
        self._rows = padded_num_keys(self.num_keys, kv)
        if self._rows >= _MAX_ROWS:
            raise ValueError(
                f"table rows {self._rows} overflow the int32 key wire"
            )
        self._shard = self._rows // kv
        self._quant_bytes = {"off": 0, "int8": 1, "int16": 2}[quant]
        self._seg = max(1, int(quant_seg))
        if self._quant_bytes:
            from parameter_server_tpu.filters.quant import SegmentQuantizer

            self._quantizer = SegmentQuantizer(self._quant_bytes, self._seg)
            self._codecs: dict[int, SegmentQuantizer] = {}
        # error-feedback accumulator (the socket handle's residual,
        # host-side): what each quantized push loses to stochastic
        # rounding, folded into the NEXT push of the same keys exactly
        # once per logical push. Dense over the padded table — the mesh
        # backend exists for tables that fit this process's devices, so
        # a (rows, vdim) f32 host mirror is bounded by the same budget.
        self._res_lock = threading.Lock()
        self._residual: np.ndarray | None = None
        self._quant_seed = itertools.count()
        self._pool = None  # lazy 1-thread executor for pull_async syncs
        sh = NamedSharding(mesh, P("kv", None))
        self.state = jax.jit(
            lambda: updater.init(self._rows, self.vdim), out_shardings=sh
        )()
        self._pull_jit, self._push_jit, self._push_q_jit = self._programs()
        # lockset race witness (PS_RACE_WITNESS=1): the residual is the
        # one piece of shared mutable host state on this backend — every
        # access must hold _res_lock or the exactly-once folding breaks
        race_track(self, ("_residual",), f"MeshBackend:{id(self):x}")

    # -- jitted programs ---------------------------------------------------

    def _programs(self):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from parameter_server_tpu.filters.quant import dequantize_flat
        from parameter_server_tpu.utils.jaxcompat import shard_map

        updater, shard, vdim = self.updater, self._shard, self.vdim
        # the non-kv mesh axes carry no state; specs stay kv-only and the
        # inputs/outputs replicate over everything else
        state_spec = P("kv", None)

        def local_pull(state_l, idx):
            begin = lax.axis_index("kv") * shard
            local = idx - begin
            ok = (local >= 0) & (local < shard)
            safe = jnp.where(ok, local, 0)
            rows = {k: jnp.take(v, safe, axis=0) for k, v in state_l.items()}
            w = updater.weights(rows)
            # merge over the server group: out-of-range rows are zero
            return lax.psum(jnp.where(ok[:, None], w, 0.0), "kv")

        def local_apply(state_l, idx_blk, g_blk):
            """The batched apply engine's single dispatch, sharded in the
            reduce-scatter shape: ``idx_blk``/``g_blk`` are this shard's
            OWN (1, C)/(1, C, vdim) segment of the push (the host's
            range fan-out already routed every row here), so the updater
            delta runs on ~U/kv rows. Pad slots carry the global pad key
            0 with zero grads: on shard 0 they scatter-add an exact-zero
            delta to the pad row (the updaters' exact-delta contract),
            on every other shard local 0 - begin is out of range and
            masked — either way the exactly-once invariant holds."""
            idx, g = idx_blk[0], g_blk[0]
            begin = lax.axis_index("kv") * shard
            local = idx - begin
            ok = (local >= 0) & (local < shard)
            safe = jnp.where(ok, local, 0)
            rows = {k: jnp.take(v, safe, axis=0) for k, v in state_l.items()}
            deltas = updater.delta(rows, g)
            mask = ok[:, None].astype(g.dtype)
            return {
                k: state_l[k].at[safe].add(mask * deltas[k]) for k in state_l
            }

        def local_apply_q(state_l, idx_blk, q_blk, qs_blk):
            # dequantize AFTER the collective boundary: what moved
            # host->mesh for THIS shard is its segment's int8/16 codes +
            # per-segment scales, not f32 gradients. The effective codec
            # segment length is a static fact of the shapes (the host
            # shrinks it for tiny pushes), so derive it here instead of
            # trusting the config closure.
            q, qs = q_blk[0], qs_blk[0]
            g = dequantize_flat(q, qs, seg=q.shape[0] // qs.shape[0])
            c = idx_blk.shape[1]
            g = g[: c * vdim].reshape(c, vdim)
            return local_apply(state_l, idx_blk, g[None])

        mesh = self.mesh

        def smap(f, in_specs, out_specs):
            return shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False,
            )

        blk = P("kv", None)
        pull = jax.jit(smap(local_pull, (state_spec, P()), P()))
        push = jax.jit(
            smap(local_apply, (state_spec, blk, P("kv", None, None)),
                 state_spec),
            donate_argnums=0,
        )
        push_q = jax.jit(
            smap(local_apply_q, (state_spec, blk, blk, blk), state_spec),
            donate_argnums=0,
        )
        return pull, push, push_q

    # -- host-side bucketing ----------------------------------------------

    @staticmethod
    def _bucket_cap(u: int) -> int:
        return 1 << max(u - 1, 0).bit_length()

    def _bucket_keys(self, keys: np.ndarray) -> tuple[np.ndarray, int]:
        keys = np.asarray(keys, dtype=np.int64)
        u = len(keys)
        cap = self._bucket_cap(u)
        idx = np.zeros(cap, dtype=np.int32)
        idx[:u] = keys  # pad slots carry PAD_KEY 0 (zero-grad semantics)
        return idx, u

    def _segment_layout(
        self, keys: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray, int]:
        """The push's reduce-scatter shaping: slice the sorted global
        keys at the shard range boundaries (contiguous because sorted —
        one searchsorted, the SocketBackend fan-out re-aimed at mesh
        shards) and pad every segment to ONE pow2 bucket ``C`` so the
        compiled-program set stays small. Returns the ("kv", C) int32
        key block (pad slots = global pad key 0), the segment bounds,
        and ``C``."""
        kv = self.mesh.shape["kv"]
        begins = np.arange(kv + 1, dtype=np.int64) * self._shard
        bounds = np.searchsorted(keys, begins)
        c = self._bucket_cap(int((bounds[1:] - bounds[:-1]).max() or 1))
        idx = np.zeros((kv, c), dtype=np.int32)
        for s in range(kv):
            idx[s, : bounds[s + 1] - bounds[s]] = keys[
                bounds[s] : bounds[s + 1]
            ]
        return idx, bounds, c

    # -- the interface -----------------------------------------------------

    def pull(self, keys: np.ndarray) -> np.ndarray:
        idx, u = self._bucket_keys(keys)
        if u == 0:
            return np.zeros((0, self.vdim), np.float32)
        flightrec.record("mesh.pull", keys=u, bucket=len(idx))
        return self._finish_pull(self._pull_jit(self.state, idx), u)

    def _finish_pull(self, dev, u: int) -> np.ndarray:
        # np.asarray is the device sync point
        return np.asarray(dev)[:u].astype(np.float32, copy=False)

    def pull_async(self, keys: np.ndarray) -> Future:
        """Non-blocking for real: the jitted gather+psum is DISPATCHED
        on the calling thread (async dispatch returns immediately) and
        only the device->host sync moves to a 1-thread executor, so a
        caller overlapping pull_async with compute actually overlaps —
        resolving inline here would hide the whole collective latency
        inside the "async" call instead."""
        idx, u = self._bucket_keys(keys)
        f: Future = Future()
        if u == 0:
            f.set_result(np.zeros((0, self.vdim), np.float32))
            return f
        flightrec.record("mesh.pull", keys=u, bucket=len(idx))
        try:
            dev = self._pull_jit(self.state, idx)
        except BaseException as e:  # noqa: BLE001 — future boundary
            f.set_exception(e)
            return f
        return self._sync_pool().submit(self._finish_pull, dev, u)

    def _sync_pool(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(max_workers=1)
        return self._pool

    def push(self, keys: np.ndarray, grads: np.ndarray) -> None:
        keys = np.asarray(keys, dtype=np.int64)
        u = len(keys)
        if u == 0:
            return
        g = np.asarray(grads, np.float32).reshape(u, -1)
        idx, bounds, c = self._segment_layout(keys)
        kv = idx.shape[0]
        if self._quant_bytes:
            q, qs, payload = self._encode_push(keys, g, idx, bounds)
            flightrec.record("mesh.push", keys=u, bytes=payload)
            flightrec.record("mesh.apply", bucket=c, quant=self._quant_bytes)
            self.state = self._push_q_jit(self.state, idx, q, qs)
        else:
            g_sh = np.zeros((kv, c, self.vdim), dtype=np.float32)
            for s in range(kv):
                g_sh[s, : bounds[s + 1] - bounds[s]] = g[
                    bounds[s] : bounds[s + 1]
                ]
            # count what actually ships (pad included) — the quant arm
            # counts its padded encoded payload the same way, so the
            # bytes ratio compares like with like
            wire_counters.inc("mesh_push_payload_bytes", int(g_sh.nbytes))
            flightrec.record("mesh.push", keys=u, bytes=int(g_sh.nbytes))
            flightrec.record("mesh.apply", bucket=c, quant=0)
            self.state = self._push_jit(self.state, idx, g_sh)

    def _encode_push(
        self,
        keys: np.ndarray,
        g: np.ndarray,
        idx: np.ndarray,
        bounds: np.ndarray,
    ) -> tuple[np.ndarray, np.ndarray, int]:
        """Quantize one push into the sharded wire layout with error
        feedback: fold the residual of the previous pushes of these
        keys, scatter the folded gradient into per-shard segment rows
        (each padded to a codec-aligned length so every row's scales
        slice is self-contained), encode with a fresh stochastic-
        rounding seed, store back what THIS encode loses. Exactly once
        per logical push — the jitted dispatch consumes the encoded
        payload as-is."""
        kv, c = idx.shape
        row = c * self.vdim
        seg_q = min(self._seg, row)
        row_pad = -(-row // seg_q) * seg_q
        codec = self._codec(seg_q)
        with self._res_lock:
            if self._residual is None:
                self._residual = np.zeros(
                    (self._rows, self.vdim), np.float32
                )
            g_tot = g + self._residual[keys]
            g_sh = np.zeros((kv, row_pad), np.float32)
            for s in range(kv):
                n = bounds[s + 1] - bounds[s]
                g_sh[s, : n * self.vdim] = g_tot[
                    bounds[s] : bounds[s + 1]
                ].ravel()
            q, qs = codec.encode(next(self._quant_seed), g_sh)
            dec = codec.decode(q, qs).reshape(kv, row_pad)
            dec_rows = np.empty_like(g_tot)
            for s in range(kv):
                n = bounds[s + 1] - bounds[s]
                dec_rows[bounds[s] : bounds[s + 1]] = dec[
                    s, : n * self.vdim
                ].reshape(n, self.vdim)
            self._residual[keys] = g_tot - dec_rows
        q = q.reshape(kv, row_pad)
        qs = qs.reshape(kv, row_pad // seg_q)
        payload = int(q.nbytes + qs.nbytes)
        wire_counters.inc("mesh_push_payload_bytes", payload)
        wire_counters.inc(
            "mesh_push_bytes_saved", max(kv * row_pad * 4 - payload, 0)
        )
        return q, qs, payload

    def _codec(self, seg_q: int):
        """The segment codec at an effective segment length (shrunk for
        pushes smaller than one configured segment, so a row's scales
        always tile it exactly)."""
        if seg_q == self._seg:
            return self._quantizer
        from parameter_server_tpu.filters.quant import SegmentQuantizer

        q = self._codecs.get(seg_q)
        if q is None:
            q = self._codecs[seg_q] = SegmentQuantizer(
                self._quant_bytes, seg_q
            )
        return q

    def push_async(self, keys: np.ndarray, grads: np.ndarray) -> Future:
        # a mesh push IS its dispatch: device-program order guarantees
        # any later pull sees it, and flush() is the applied barrier —
        # so the future resolves at accept time (class docstring)
        f: Future = Future()
        try:
            self.push(keys, grads)
            f.set_result(None)
        except BaseException as e:  # noqa: BLE001 — future boundary
            f.set_exception(e)
        return f

    def flush(self) -> None:
        import jax

        jax.block_until_ready(list(self.state.values()))

    def close(self) -> None:
        self.flush()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def weights(self) -> np.ndarray:
        from parameter_server_tpu.kv.store import materialize_weights

        w = np.asarray(materialize_weights(self.updater, self.state))
        return w[: self.num_keys].reshape(self.num_keys, self.vdim)

    def residual_norm(self) -> float:
        """Mean |residual| over the table (observability + the tests'
        telescoping identity; mirrors ServerHandle.residual_norm)."""
        with self._res_lock:
            if self._residual is None:
                return 0.0
            return float(np.abs(self._residual).mean())

    def residual_rows(self, keys: np.ndarray) -> np.ndarray:
        """Current residual rows for global ``keys`` (zeros before the
        first quantized push) — read-only."""
        idx = np.asarray(keys, np.int64)
        with self._res_lock:
            if self._residual is None:
                return np.zeros((len(idx), self.vdim), np.float32)
            return self._residual[idx].copy()

    def stats(self) -> dict[str, Any]:
        return {
            "backend": "mesh",
            "kv_shards": self.mesh.shape["kv"],
            "table_rows": self._rows,
            "quant_bytes": self._quant_bytes,
            "residual_mean_abs": self.residual_norm(),
        }
